"""Mesh-sharded serving + elastic membership tests (``pytest -m
serve_mesh`` / ``make serve_mesh``) — docs/SERVING.md "Mesh-sharded
serving and elastic autoscaling".

Covers the tentpole contracts on the 8-virtual-device CPU mesh (conftest):

1. ``parallel.mesh_slices`` — disjoint replica-group slices covering the
   mesh;
2. sharded ``InferenceEngine`` equivalence — a 1×1 mesh is *bitwise*
   identical to the unsharded engine per bucket; tp>1 matches to float
   tolerance, is bitwise-vs-its-own-``predict`` (the per-shard-config
   contract), and the compiled-program bound stays TraceLinter-green;
3. sharded hot reload — the new generation lands with the SAME shardings,
   aval drift still rejected;
4. ``ReplicaPool.sharded`` + Router — data-parallel replica groups on mesh
   slices answer bitwise-identically to each other, and a killed group
   fails over (graceful degradation is mesh-independent);
5. elastic membership — quarantine → activate-at-a-generation-boundary
   joins, drain-then-leave scale-in with ZERO requests lost under
   concurrent traffic;
6. fleet stats export — ``ReplicaPool.stats()`` members + per-replica
   ``fleet.replica<i>.*`` gauges land in the Prometheus exposition, and a
   removed replica's gauges are dropped.
"""
import threading
import time

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu import serve
from mxnet_tpu import symbol as sym
from mxnet_tpu.analysis.trace import TraceLinter
from mxnet_tpu.parallel.sharding import ShardingRules
from mxnet_tpu.serve import ServeClient, ServeError, ServeServer
from mxnet_tpu.serve.fleet import FleetServer, ReplicaPool, Router

pytestmark = [pytest.mark.serve, pytest.mark.serve_mesh]


def _mlp():
    rng = np.random.RandomState(7)
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=64, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=8, name="fc2")
    net = sym.softmax(net, name="prob")
    arg = {"fc1_weight": rng.randn(64, 32).astype(np.float32) * 0.1,
           "fc1_bias": rng.randn(64).astype(np.float32) * 0.01,
           "fc2_weight": rng.randn(8, 64).astype(np.float32) * 0.1,
           "fc2_bias": np.zeros(8, np.float32)}
    return net, arg


def _rules():
    # fc1 row-parallel (output dim), fc2 column-parallel (input dim) —
    # the classic Megatron split: one all-reduce at fc2's output
    return ShardingRules([("fc1_weight|fc1_bias", P("tp")),
                          ("fc2_weight", P(None, "tp"))])


def _sharded_server_factory(net, arg, engines=None):
    def make_server(submesh):
        eng = serve.InferenceEngine(net, arg, max_batch_size=8, lint="off",
                                    mesh=submesh, rules=_rules())
        eng.warmup((32,))
        if engines is not None:
            engines.append(eng)
        srv = ServeServer(eng, port=0, max_linger_ms=0.0)
        srv.start()
        return srv
    return make_server


X = np.random.RandomState(3).rand(3, 32).astype(np.float32)


# ---------------------------------------------------------------------------
# 1. mesh slices
# ---------------------------------------------------------------------------

def test_mesh_slices_partition_the_mesh():
    mesh = par.make_mesh({"dp": 4, "tp": 2})
    slices = par.mesh_slices(mesh, "dp")
    assert len(slices) == 4
    assert all(s.axis_names == ("tp",) for s in slices)
    seen = [d.id for s in slices for d in s.devices.flat]
    assert sorted(seen) == sorted(d.id for d in mesh.devices.flat)
    assert len(set(seen)) == 8  # disjoint cover

    # pure-dp mesh → 1-device slices with a trivial tp axis
    slices = par.mesh_slices(par.make_mesh({"dp": 8}), "dp")
    assert len(slices) == 8
    assert all(par.mesh_axes(s) == {"tp": 1} for s in slices)

    # mesh without the axis is one slice: itself
    tp_mesh = par.make_mesh({"tp": 8})
    assert par.mesh_slices(tp_mesh, "dp") == [tp_mesh]


# ---------------------------------------------------------------------------
# 2. sharded-engine equivalence
# ---------------------------------------------------------------------------

def test_sharded_engine_1x1_mesh_bitwise_per_bucket():
    """On a 1×1 mesh the sharded engine is the unsharded engine: the same
    traced fn on the same device must produce BITWISE-identical outputs
    for every bucket."""
    net, arg = _mlp()
    plain = serve.InferenceEngine(net, arg, max_batch_size=8, lint="off")
    mesh1 = par.make_mesh({"tp": 1}, devices=[jax.devices()[0]])
    sharded = serve.InferenceEngine(net, arg, max_batch_size=8, lint="off",
                                    mesh=mesh1, rules=_rules())
    rng = np.random.RandomState(0)
    for n in (1, 2, 3, 5, 8):  # one request per bucket incl. padded sizes
        x = rng.rand(n, 32).astype(np.float32)
        a = plain.predict(x)
        b = sharded.predict(x)
        assert (a == b).all(), f"bucket for n={n} not bitwise"
    assert sharded.num_programs == plain.num_programs


def test_sharded_engine_tp_equivalence_and_program_bound():
    net, arg = _mlp()
    plain = serve.InferenceEngine(net, arg, max_batch_size=8, lint="off")
    mesh = par.make_mesh({"tp": 4})
    eng = serve.InferenceEngine(net, arg, max_batch_size=8, lint="off",
                                mesh=mesh, rules=_rules())
    st = eng.stats()
    assert st["mesh"] == {"tp": 4} and st["mesh_devices"] == 4
    assert st["sharded_params"] == 3  # fc1_weight, fc1_bias, fc2_weight

    # outputs match the unsharded engine to float tolerance (XLA does not
    # promise identical ulps across different programs)...
    rng = np.random.RandomState(1)
    for n in (1, 4, 7, 8):
        x = rng.rand(n, 32).astype(np.float32)
        np.testing.assert_allclose(plain.predict(x), eng.predict(x),
                                   rtol=1e-5, atol=1e-6)
    # ...and repeated serving is bitwise-vs-predict PER SHARD CONFIG
    x = rng.rand(5, 32).astype(np.float32)
    ref = eng.predict(x)
    for _ in range(3):
        assert (eng.predict(x) == ref).all()

    # oversize request chunks through the top bucket, still correct
    big = rng.rand(19, 32).astype(np.float32)
    np.testing.assert_allclose(plain.predict(big), eng.predict(big),
                               rtol=1e-5, atol=1e-6)

    # the compiled-program bound holds under tp>1: one program per bucket,
    # proven by the TraceLinter serve-retrace-churn rule (empty = proof)
    assert eng.num_programs <= len(eng.buckets)
    assert TraceLinter().check_serve_engine(eng) == []


def test_sharded_engine_warmup_and_linter_green():
    net, arg = _mlp()
    mesh = par.make_mesh({"tp": 2})
    eng = serve.InferenceEngine(net, arg, max_batch_size=8, lint="off",
                                mesh=mesh, rules=_rules())
    compiled = eng.warmup((32,))
    assert compiled == len(eng.buckets)
    # warmed buckets never recompile: ragged traffic reuses the programs
    before = len(eng.compile_log)
    rng = np.random.RandomState(2)
    for n in (1, 3, 6, 8, 2, 5):
        eng.predict(rng.rand(n, 32).astype(np.float32))
    assert len(eng.compile_log) == before
    assert TraceLinter().check_serve_engine(eng) == []


def test_sharded_engine_reload_keeps_shardings():
    net, arg = _mlp()
    mesh = par.make_mesh({"tp": 2})
    eng = serve.InferenceEngine(net, arg, max_batch_size=8, lint="off",
                                mesh=mesh, rules=_rules())
    x = X.copy()
    out0 = eng.predict(x)
    compiles0 = len(eng.compile_log)

    arg2 = {k: np.asarray(v) * 2.0 for k, v in arg.items()}
    staged = eng.prepare_reload(arg2)
    assert eng.version == 0  # staged, not serving
    assert eng.commit_reload() == staged == 1
    out1 = eng.predict(x)
    assert not np.allclose(out0, out1)
    # reload is retrace-free even sharded: params are traced args, the
    # new generation landed with the construction-time shardings
    assert len(eng.compile_log) == compiles0
    assert TraceLinter().check_serve_engine(eng) == []

    # aval drift still rejected (would silently recompile every bucket)
    bad = dict(arg2)
    bad["fc1_weight"] = np.zeros((32, 64), np.float32)
    with pytest.raises(ServeError, match="aval mismatch"):
        eng.prepare_reload(bad)


# ---------------------------------------------------------------------------
# 3. replica groups on mesh slices behind the Router
# ---------------------------------------------------------------------------

def test_sharded_pool_replica_groups_bitwise_and_failover():
    net, arg = _mlp()
    engines = []
    pool = ReplicaPool.sharded(_sharded_server_factory(net, arg, engines),
                               groups=2, probe_interval=0.1,
                               backoff_base=0.05, backoff_cap=0.5)
    pool.start()
    try:
        assert len(pool.ready_members()) == 2
        assert pool.spare_slices == 0
        # each group's engine sits on its own disjoint 4-device slice
        ids = [sorted(d.id for d in e.mesh.devices.flat) for e in engines]
        assert not (set(ids[0]) & set(ids[1]))
        assert len(ids[0]) == len(ids[1]) == 4

        router = Router(pool)
        front = FleetServer(router, port=0)
        front.start()
        cli = ServeClient("127.0.0.1", front.port)
        try:
            ref = engines[0].predict(X)  # per-shard-config oracle
            outs = [np.asarray(cli.infer(X)) for _ in range(6)]
            # round-robin hits both groups; same shard config ⇒ bitwise
            assert all((o == ref).all() for o in outs)

            # kill one replica group: traffic fails over, answers stay
            # bitwise — graceful degradation is mesh-independent
            pool.kill(0)
            for _ in range(4):
                assert (np.asarray(cli.infer(X, deadline_ms=5000)) ==
                        ref).all()
        finally:
            cli.close()
            front.stop()
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# 4. elastic membership: quarantine→activate joins, drain-then-leave
# ---------------------------------------------------------------------------

def test_elastic_join_activates_at_generation_boundary():
    net, arg = _mlp()
    pool = ReplicaPool.sharded(_sharded_server_factory(net, arg),
                               groups=4, start=1, probe_interval=0.1)
    pool.start()
    try:
        assert len(pool.ready_members()) == 1
        assert pool.spare_slices == 3
        gen0 = pool.generation
        idx = pool.add_replica(pool.new_sharded_handle(), wait_ready=True)
        assert pool._members[idx].state == "ready"
        assert len(pool.ready_members()) == 2
        assert pool.generation == gen0 + 1  # exactly one boundary
        assert pool.spare_slices == 2
        st = pool.stats()
        assert st["members"][str(idx)]["state"] == "ready"
        assert st["generation"] == pool.generation
    finally:
        pool.stop()


def test_elastic_scale_in_drains_with_zero_lost():
    """Scale-in under concurrent traffic: deactivation at the boundary
    stops new routing, the drain finishes queued + in-flight work, and
    every client request still gets a correct answer — zero lost."""
    net, arg = _mlp()
    pool = ReplicaPool.sharded(_sharded_server_factory(net, arg),
                               groups=2, probe_interval=0.1)
    pool.start()
    router = Router(pool)
    ref = None
    errors = []
    results = []
    stop = threading.Event()

    def traffic():
        while not stop.is_set():
            try:
                outs, _v = router.infer([X], deadline_ms=5000)
                results.append(outs[0])
            except Exception as e:  # noqa: BLE001 — the assertion target
                errors.append(e)

    try:
        ref = np.asarray(router.infer([X])[0][0])
        threads = [threading.Thread(target=traffic) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        victim = max(pool.ready_members(), key=lambda m: m.idx)
        assert pool.remove_replica(victim.idx, drain_timeout=10.0)
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, f"lost {len(errors)} requests: {errors[:3]}"
        assert len(pool.ready_members()) == 1
        assert pool._members[victim.idx].state == "removed"
        assert pool.spare_slices == 1  # the slice came back
        assert all((np.asarray(r) == ref).all() for r in results)
        # the freed slice is reusable: join again onto it
        idx = pool.add_replica(pool.new_sharded_handle(), wait_ready=True)
        assert len(pool.ready_members()) == 2
        assert pool._members[idx].state == "ready"
    finally:
        stop.set()
        router.close(timeout=5)
        pool.stop()


def test_remove_replica_idempotent_and_supervisor_leaves_leavers_alone():
    net, arg = _mlp()
    pool = ReplicaPool.sharded(_sharded_server_factory(net, arg),
                               groups=2, probe_interval=0.05)
    pool.start()
    try:
        assert pool.remove_replica(1, drain_timeout=5.0)
        assert pool.remove_replica(1, drain_timeout=5.0)  # idempotent
        gen = pool.generation
        # the supervisor must NOT resurrect the leaver
        time.sleep(0.4)
        assert pool._members[1].state == "removed"
        assert pool.generation == gen
        assert len(pool.ready_members()) == 1
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# 5. fleet stats → Prometheus exposition
# ---------------------------------------------------------------------------

def test_fleet_stats_exported_to_prometheus():
    from mxnet_tpu import obs
    from mxnet_tpu.obs.export import to_prometheus

    net, arg = _mlp()
    obs.enable()
    try:
        pool = ReplicaPool.sharded(_sharded_server_factory(net, arg),
                                   groups=2, probe_interval=0.1)
        pool.start()
        router = Router(pool)
        try:
            # traffic so the batcher has occupancy to report
            for _ in range(4):
                router.infer([X])
            deadline = time.monotonic() + 5.0
            snap = {}
            while time.monotonic() < deadline:
                snap = obs.metrics.snapshot()["gauges"]
                if "fleet.replica0.queue_depth" in snap \
                        and "fleet.replica1.queue_depth" in snap:
                    break
                time.sleep(0.1)
            assert "fleet.replica0.queue_depth" in snap
            assert "fleet.replica1.occupancy" in snap
            assert snap.get("fleet.replicas_total") == 2
            assert "fleet.generation" in snap
            router.stats()  # mirrors per-replica breaker state
            snap = obs.metrics.snapshot()["gauges"]
            assert snap.get("fleet.replica0.breaker_state") == 0  # closed

            # the SAME numbers ride the pool's stats dict (autoscaler view)
            pst = pool.stats()
            assert set(pst["members"]) == {"0", "1"}
            for v in pst["members"].values():
                assert {"state", "queue_depth", "occupancy"} <= set(v)

            # and render in the text exposition
            text = to_prometheus(obs.metrics.snapshot())
            assert "mxnet_fleet_replica0_queue_depth" in text
            assert "mxnet_fleet_generation" in text

            # scale-in drops the removed replica's gauges
            pool.remove_replica(1, drain_timeout=5.0)
            gone = obs.metrics.snapshot()["gauges"]
            assert "fleet.replica1.queue_depth" not in gone
            assert "fleet.replica1.breaker_state" not in gone
        finally:
            router.close(timeout=5)
            pool.stop()
    finally:
        obs.disable()
        obs.reset()
