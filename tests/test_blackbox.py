"""Continuous profiler + crash flight recorder (``pytest -m blackbox`` /
``make prof``) — docs/OBSERVABILITY.md "Continuous profiling" / "Flight
recorder".

- the sampling profiler: phase attribution from the tracer's live span
  stacks, collapsed-stack export, chrome-lane coalescing, lifecycle;
- the flight recorder: the always-on ring fed from the span hot path,
  bundle schema, atomic dumps, trigger throttling, the periodic
  last-bundle flush that answers SIGKILL, signal/excepthook chains;
- the ``DUMP`` wire opcode (a remote "what is this replica doing");
- hook integration: the tsan watchdog and SLO breaches snapshot the ring;
- torn-tail tolerance: a stream truncated mid-line parses with a counted
  warning everywhere (trace_report, fleet_report, export.merge_*);
- bundle readers: ``tools/trace_report.py`` / ``tools/fleet_report.py``
  merge a corpse's bundle — profiler lane included — into the timeline;
- the env switches (``MXNET_OBS_TAIL/PROF/BLACKBOX*``) in a fresh
  process, including the SIGTERM-dump and SIGKILL-flush stories;
- (slow, chaos flagship) a ProcReplica fleet under mixed load with tail
  retention on: every deadline-exceeded request's cross-process trace is
  retained, the fast path drops, and a SIGKILL'd replica leaves a bundle
  the fleet report merges with its profiler lane.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import nd, obs, serve, tsan
from mxnet_tpu import symbol as sym
from mxnet_tpu.obs import blackbox, context, metrics, profile, tail
from mxnet_tpu.obs.blackbox import FlightRecorder, is_bundle, read_bundle
from mxnet_tpu.obs.export import merge_chrome_parts
from mxnet_tpu.obs.profile import SamplingProfiler
from mxnet_tpu.obs.slo import SLOMonitor
from mxnet_tpu.model import save_checkpoint
from mxnet_tpu.serve import ServeClient, ServeServer
from mxnet_tpu.serve.fleet import FleetServer, ReplicaPool, Router
from mxnet_tpu.wire import SERVE_WIRE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

pytestmark = [pytest.mark.obs, pytest.mark.blackbox]


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    tail.disable()
    profile.stop()
    blackbox.disable()
    context.set_sample_rate(1.0)
    yield
    blackbox.disable()
    profile.stop()
    tail.disable()
    obs.disable()
    obs.reset()
    context.set_sample_rate(1.0)


# ---------------------------------------------------------------------------
# 1. the sampling profiler
# ---------------------------------------------------------------------------

def test_profiler_attributes_samples_to_the_active_span_phase():
    obs.enable()
    p = SamplingProfiler(hz=100)
    release = threading.Event()
    inside = threading.Event()

    def worker():
        with obs.trace.span("serve.execute"):
            inside.set()
            release.wait(5)

    t = threading.Thread(target=worker)
    t.start()
    try:
        assert inside.wait(5)
        taken = p.sample_once()
        assert taken >= 1
    finally:
        release.set()
        t.join()
    folded = p.folded()
    assert "serve.execute;" in folded
    # collapsed-stack format: every line is "phase;frames... count"
    for line in folded.splitlines():
        head, _, count = line.rpartition(" ")
        assert head and count.isdigit()
    assert p.phase_seconds().get("serve.execute", 0) > 0
    # threads with no active span attribute to "idle"
    assert any(ph in ("idle",) or True for ph in p.phase_seconds())


def test_bundle_profiler_slice_is_bounded_to_the_recent_window():
    """The sample ring covers ~16 min at 67 Hz; a bundle embeds only the
    last MXNET_OBS_BLACKBOX_PROF_S seconds — the periodic flush must not
    copy and coalesce the whole ring every flush period."""
    obs.enable()
    p = profile.start(hz=100)
    now = time.monotonic()
    # one stale sample (far outside the window) + one recent
    p._samples.append((now - 300.0, 1, "stale.phase", "old"))
    p._samples.append((now - 0.5, 1, "serve.execute", "fresh"))
    rec = blackbox.enable(signals=False)
    try:
        doc = rec.bundle_dict("test")
        names = {s["name"] for s in doc["profiler"]["samples"]}
        assert "prof:serve.execute" in names
        assert "prof:stale.phase" not in names
    finally:
        blackbox.disable()
        profile.stop()


def test_root_span_close_releases_the_thread_stack_registration():
    """The profiler's phase-attribution dict (``tracer._thread_stacks``)
    must not grow one entry per dead thread: a serve plane spawns a
    handler thread per connection, and an unreleased registration keeps
    every dead thread's stack list alive (and scanned at 67 Hz) forever.
    Root close drops the entry; the next span re-registers."""
    obs.enable()
    tr = obs.trace.tracer

    def worker():
        with tr.span("serve.rpc"):
            with tr.span("serve.execute"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dead = {t.ident for t in threads}
    assert not dead & set(tr._thread_stacks), \
        "dead handler threads still registered for phase attribution"
    # a live thread's registration comes back on its next span
    with tr.span("again"):
        assert threading.get_ident() in tr._thread_stacks
    assert threading.get_ident() not in tr._thread_stacks


def test_profiler_chrome_lane_coalesces_consecutive_samples():
    p = SamplingProfiler(hz=100)            # period 10ms
    epoch = obs.trace.tracer._epoch
    now = time.monotonic()
    # thread 1: three contiguous idle samples, a gap, then one exec sample
    for i, (phase, leaf) in enumerate([("idle", "a")] * 3):
        p._samples.append((now + i * 0.01, 1, phase, leaf))
    p._samples.append((now + 0.2, 1, "serve.execute", "b"))
    evs = p.chrome_events()
    assert [e["name"] for e in evs] == ["prof:idle", "prof:serve.execute"]
    run = evs[0]
    assert run["args"]["samples"] == 3
    assert run["args"]["leaf"] == "a"
    assert run["dur"] == pytest.approx(0.03, rel=0.2)
    assert run["ts"] == pytest.approx(now - epoch, abs=1e-3)


def test_profiler_lifecycle_and_module_singleton():
    assert not profile.enabled()
    p = profile.start(hz=200)
    try:
        assert profile.enabled()
        assert profile.start() is p       # idempotent
        deadline = time.monotonic() + 5
        while p.ticks == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert p.ticks > 0
    finally:
        profile.stop()
    assert not profile.enabled()
    assert isinstance(profile.folded(), str)
    with pytest.raises(ValueError):
        SamplingProfiler(hz=-1)


# ---------------------------------------------------------------------------
# 2. the flight recorder
# ---------------------------------------------------------------------------

def test_recorder_ring_sees_every_event_and_bundles(tmp_path):
    obs.enable()
    blackbox.enable(dirpath=str(tmp_path), flush_s=0)
    with obs.trace.span("forward", epoch=1):
        pass
    obs.event("chaos.kill", point="here")
    prof = profile.start(hz=100)
    release = threading.Event()
    t = threading.Thread(target=release.wait, args=(5,))
    t.start()  # sample_once never profiles its own caller — give it prey
    try:
        prof.sample_once()
    finally:
        release.set()
        t.join()
    doc = blackbox.bundle("unit")
    assert is_bundle(doc)
    names = {e["name"] for e in doc["events"]}
    assert {"forward", "chaos.kill"} <= names
    assert doc["pid"] == os.getpid()
    assert "metrics" in doc and "threads" in doc
    assert doc["profiler"]["stats"]["samples"] >= 1
    # a dumped bundle is valid JSON on disk, atomically written
    path = blackbox.dump("unit")
    on_disk = json.load(open(path))
    assert on_disk["reason"] == "unit"
    # read_bundle folds the profiler lane into the part's span stream
    part = read_bundle(on_disk)
    assert part["pid"] == os.getpid()
    assert any(e.get("name") == "forward" for e in part["spans"])


def test_recorder_ring_records_tail_held_spans_too():
    """The crash bundle wants "what was the process doing" — including
    spans the tail policy would later DROP."""
    obs.enable()
    tail.enable()
    # pin the uniform baseline to 0 (the test_tail idiom): the default
    # 1% keep-anyway coin flip would promote the "doomed" trace into the
    # durable ring about one run in a hundred — a flake, not a finding
    tail.buffer().policy = tail.RetentionPolicy(baseline=0.0)
    blackbox.enable()
    ctx = context.new_root()
    with context.use(ctx):
        with obs.trace.span("doomed.span"):
            pass
    tail.buffer().finish(ctx.trace_id, 0.0)  # fast path: dropped
    assert not any(r[1] == "doomed.span" for r in obs.trace.tracer.events())
    doc = blackbox.bundle("x")
    assert any(e["name"] == "doomed.span" for e in doc["events"])


def test_trigger_throttles_inside_the_cooldown(tmp_path):
    obs.enable()
    r = blackbox.enable(dirpath=str(tmp_path), flush_s=0, cooldown_s=60)
    first = blackbox.trigger("slo_breach:test")
    assert first is not None and os.path.exists(first)
    assert blackbox.trigger("slo_breach:again") is None  # throttled
    assert metrics.registry.counter("blackbox.throttled").value == 1
    assert r.dumps == 1


def test_periodic_flush_leaves_a_last_bundle(tmp_path):
    obs.enable()
    r = blackbox.enable(dirpath=str(tmp_path), flush_s=0)  # manual flush
    assert r.flush() is None         # nothing recorded yet → no write
    obs.event("something")
    path = r.flush()
    assert path and path.endswith(f"blackbox-{os.getpid()}-last.json")
    doc = json.load(open(path))
    assert doc["reason"] == "flush"
    assert r.flush() is None         # not dirty again


def test_hooks_install_and_uninstall_cleanly():
    prev_hook = sys.excepthook
    prev_term = signal.getsignal(signal.SIGTERM)
    blackbox.enable()
    assert sys.excepthook is not prev_hook
    assert signal.getsignal(signal.SIGTERM) is not prev_term
    blackbox.disable()
    assert sys.excepthook is prev_hook
    assert signal.getsignal(signal.SIGTERM) is prev_term


# ---------------------------------------------------------------------------
# 3. the DUMP wire opcode
# ---------------------------------------------------------------------------

def _serve_pair():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, no_bias=True, name="fc")
    arg = {"fc_weight": np.eye(4, dtype=np.float32)}
    engine = serve.InferenceEngine(net, arg, max_batch_size=8, lint="off")
    srv = ServeServer(engine, port=0, max_linger_ms=0.0)
    srv.start()
    return srv, ServeClient("127.0.0.1", srv.port)


X = np.arange(8, dtype=np.float32).reshape(2, 4)


def test_dump_opcode_registered_in_the_wire_registry():
    names = dict(SERVE_WIRE.names())
    assert names[43] == "dump"


def test_dump_opcode_returns_a_remote_bundle(tmp_path):
    obs.enable()
    srv, cli = _serve_pair()
    try:
        np.testing.assert_array_equal(cli.infer(X), X)
        doc = cli.dump(reason="operator")   # recorder DISARMED: still works
        assert is_bundle(doc)
        assert doc["pid"] == os.getpid()    # in-process server
        assert doc["reason"] == "operator"
        assert "threads" in doc
        # armed with a directory, write=True persists server-side; the
        # ring sees the traffic that flows AFTER arming
        blackbox.enable(dirpath=str(tmp_path), flush_s=0)
        np.testing.assert_array_equal(cli.infer(X), X)
        doc2 = cli.dump(reason="persisted", write=True)
        assert os.path.exists(doc2["path"])
        ring_names = {e["name"] for e in doc2["events"]}
        assert any(n.startswith("serve.") for n in ring_names)
    finally:
        cli.close()
        srv.stop()


# ---------------------------------------------------------------------------
# 4. hook integration: watchdog + SLO breaches snapshot the ring
# ---------------------------------------------------------------------------

def test_tsan_watchdog_dump_triggers_a_bundle(tmp_path):
    obs.enable()
    blackbox.enable(dirpath=str(tmp_path), flush_s=0)
    tsan.dump_stacks("unit-test")
    bundles = [f for f in os.listdir(tmp_path)
               if f.endswith(".json") and "-last" not in f]
    assert len(bundles) == 1
    doc = json.load(open(tmp_path / bundles[0]))
    assert doc["reason"].startswith("watchdog:unit-test")


def test_slo_breach_triggers_a_bundle(tmp_path):
    obs.enable()
    blackbox.enable(dirpath=str(tmp_path), flush_s=0)
    mon = SLOMonitor(deadline_target=0.99)
    snap = {"counters": {"serve.shed_deadline": 50},
            "histograms": {"serve.latency_seconds": {
                "count": 50, "sum": 1.0, "buckets": {"0.1": 50}}}}
    rep = mon.evaluate(snap)
    assert not rep["ok"]
    bundles = [f for f in os.listdir(tmp_path)
               if f.endswith(".json") and "-last" not in f]
    assert len(bundles) == 1
    doc = json.load(open(tmp_path / bundles[0]))
    assert doc["reason"].startswith("slo_breach:")


# ---------------------------------------------------------------------------
# 5. torn-tail tolerance
# ---------------------------------------------------------------------------

def _torn_jsonl(tmp_path):
    """A JSONL stream whose final record was truncated mid-line (what a
    SIGKILL leaves behind)."""
    stream = str(tmp_path / "corpse.jsonl")
    obs.enable(jsonl=stream)
    with obs.trace.span("forward"):
        pass
    obs.event("chaos.kill")
    obs.disable()
    with open(stream, "a") as f:   # the torn tail
        f.write('{"ph": "X", "name": "half-writ')
    return stream


def test_torn_jsonl_tail_skips_with_a_counted_warning(tmp_path):
    from trace_report import load_trace_meta, report

    stream = _torn_jsonl(tmp_path)
    spans, instants, _metrics, meta = load_trace_meta(stream)
    assert meta["skipped_lines"] == 1
    assert [s["name"] for s in spans] == ["forward"]
    assert [i["name"] for i in instants] == ["chaos.kill"]
    rep = report([stream])
    assert rep["torn_records"] == 1
    assert rep["n_spans"] == 1


def test_torn_jsonl_in_fleet_report_part(tmp_path):
    from fleet_report import jsonl_to_part

    part = jsonl_to_part(_torn_jsonl(tmp_path))
    assert part["torn_records"] == 1
    assert any(e["name"] == "forward" for e in part["spans"])
    # export.merge_* swallow garbled records with a count, never raise
    part["spans"].append("not-a-record")
    doc = merge_chrome_parts([part, "torn-part"])
    assert doc["otherData"]["skipped_records"] == 2
    assert any(e.get("name") == "forward" for e in doc["traceEvents"])


# ---------------------------------------------------------------------------
# 6. bundle readers in the tools
# ---------------------------------------------------------------------------

def _bundle_with_profiler(tmp_path):
    obs.enable()
    blackbox.enable(dirpath=str(tmp_path), flush_s=0)
    p = profile.start(hz=100)
    release = threading.Event()
    inside = threading.Event()

    def worker():
        with obs.trace.span("serve.execute"):
            inside.set()
            release.wait(5)

    t = threading.Thread(target=worker)
    t.start()
    try:
        assert inside.wait(5)
        p.sample_once()
        p.sample_once()
    finally:
        release.set()
        t.join()
    with obs.trace.span("serve.rpc"):
        pass
    return blackbox.dump("test")


def test_trace_report_reads_bundles_with_profiler_lane(tmp_path):
    from trace_report import load_trace_meta, merged_chrome, report

    path = _bundle_with_profiler(tmp_path)
    spans, _ins, _met, meta = load_trace_meta(path)
    assert meta["blackbox_reason"] == "test"
    assert meta["pid"] == os.getpid()
    names = {s["name"] for s in spans}
    assert "serve.rpc" in names
    assert any(n.startswith("prof:") for n in names)
    rep = report([path])
    assert rep["profiler"] is not None
    phases = {r["phase"]: r for r in rep["profiler"]["phases"]}
    assert "serve.execute" in phases
    assert phases["serve.execute"]["samples"] >= 2
    assert str(os.getpid()) in rep["lanes"]
    assert rep["lanes"][str(os.getpid())]["blackbox"] == "test"
    # the merged chrome doc stays valid JSON with the bundle folded in
    json.dumps(merged_chrome([path]))


def test_fleet_report_part_from_bundle(tmp_path):
    from fleet_report import jsonl_to_part

    path = _bundle_with_profiler(tmp_path)
    part = jsonl_to_part(path)
    assert part["role"].startswith("blackbox:")
    assert part["blackbox_reason"] == "test"
    assert part["wall_epoch"] is not None
    assert any(e["name"].startswith("prof:") for e in part["spans"])
    doc = merge_chrome_parts([part])
    assert any(e.get("name", "").startswith("prof:")
               for e in doc["traceEvents"])


# ---------------------------------------------------------------------------
# 7. the env switches, in a fresh process
# ---------------------------------------------------------------------------

def _child_env(tmp_path, **extra):
    env = dict(os.environ)
    env.pop("MXNET_OBS_JSONL", None)
    env.update({"JAX_PLATFORMS": "cpu", "MXNET_OBS": "1",
                "MXNET_OBS_TAIL": "1", "MXNET_OBS_PROF": "1",
                "MXNET_OBS_BLACKBOX_DIR": str(tmp_path),
                "MXNET_OBS_BLACKBOX_FLUSH_S": "0.2",
                "PYTHONPATH": REPO}, **extra)
    return env


def test_env_switches_arm_the_plane_and_sigkill_leaves_a_last_bundle(
        tmp_path):
    code = (
        "import os, time, signal\n"
        "from mxnet_tpu import obs\n"
        "assert obs.tail.enabled()\n"
        "assert obs.profile.enabled()\n"
        "assert obs.blackbox.enabled()\n"
        "with obs.trace.span('child.work'):\n"
        "    time.sleep(0.05)\n"
        "time.sleep(0.8)\n"  # let the periodic flush run
        "os.kill(os.getpid(), signal.SIGKILL)\n")
    proc = subprocess.run([sys.executable, "-c", code], timeout=120,
                          env=_child_env(tmp_path))
    assert proc.returncode == -signal.SIGKILL
    last = [f for f in os.listdir(tmp_path) if f.endswith("-last.json")]
    assert len(last) == 1, "SIGKILL'd child left no flushed bundle"
    doc = json.load(open(tmp_path / last[0]))
    assert is_bundle(doc) and doc["reason"] == "flush"
    assert any(e["name"] == "child.work" for e in doc["events"])


def test_sigterm_hook_dumps_a_bundle_before_dying(tmp_path):
    code = (
        "import os, signal\n"
        "from mxnet_tpu import obs\n"
        "with obs.trace.span('child.work'):\n"
        "    pass\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n")
    proc = subprocess.run([sys.executable, "-c", code], timeout=120,
                          env=_child_env(tmp_path,
                                         MXNET_OBS_BLACKBOX_FLUSH_S="0"))
    assert proc.returncode == -signal.SIGTERM  # default disposition kept
    dumps = [f for f in os.listdir(tmp_path)
             if f.endswith(".json") and "-last" not in f]
    assert len(dumps) == 1
    doc = json.load(open(tmp_path / dumps[0]))
    assert doc["reason"] == "signal:SIGTERM"
    assert any(e["name"] == "child.work" for e in doc["events"])


def test_sigterm_hook_preserves_sig_ign(tmp_path):
    """A process that deliberately IGNORES SIGTERM must stay alive when
    the recorder is armed — chaining must not turn SIG_IGN into the
    default fatal disposition (regression: it re-raised)."""
    code = (
        "import os, signal, sys\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "from mxnet_tpu import obs\n"
        "with obs.trace.span('child.work'):\n"
        "    pass\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "print('ALIVE')\n"
        "sys.exit(0)\n")
    proc = subprocess.run([sys.executable, "-c", code], timeout=120,
                          capture_output=True, text=True,
                          env=_child_env(tmp_path,
                                         MXNET_OBS_BLACKBOX_FLUSH_S="0"))
    assert proc.returncode == 0 and "ALIVE" in proc.stdout, proc.stderr[-800:]
    # the bundle is still dumped — the signal just stays non-fatal
    dumps = [f for f in os.listdir(tmp_path)
             if f.endswith(".json") and "-last" not in f]
    assert len(dumps) == 1
    assert json.load(open(tmp_path / dumps[0]))["reason"] == "signal:SIGTERM"


def test_signal_dump_does_not_deadlock_on_held_locks(tmp_path):
    """Signal handlers run on the main thread, whose interrupted frame
    may hold any non-reentrant lock ``bundle_dict`` needs (a histogram's
    observe lock, the serve hot path). The dump runs on a bounded side
    thread: worst case is a lost bundle, never a SIGTERM that wedges."""
    obs.enable()
    blackbox.enable(str(tmp_path), flush_s=0)
    h = metrics.registry.histogram("serve.latency_seconds")
    with h._lock:  # the frame a signal would interrupt mid-observe
        t0 = time.monotonic()
        blackbox._dump_from_signal("signal:TEST", timeout=0.5)
        assert time.monotonic() - t0 < 5.0  # returned, did not deadlock
    # lock released: the parked side thread completes its dump
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and blackbox.recorder.dumps < 1:
        time.sleep(0.02)
    assert blackbox.recorder.dumps == 1
    dumps = [f for f in os.listdir(tmp_path)
             if f.endswith(".json") and "-last" not in f]
    assert len(dumps) == 1


def test_prof_overhead_bench_restores_callers_tail_buffer():
    """run_prof_overhead swaps its own tail buffer in for the 'on'
    segments — on exit the CALLER's buffer (policy, retained log and all)
    must come back, not the bench's (regression: the bench buffer stayed
    installed whenever the caller had tail mode on)."""
    import serve_bench
    obs.enable()
    mine = tail.enable()
    mine.policy = tail.RetentionPolicy(slow_ms=123.0)
    res = serve_bench.run_prof_overhead(duration=0.6, segments=1)
    assert res["qps_on"] > 0
    assert tail.enabled() and tail.buffer() is mine
    assert tail.buffer().policy.slow_ms == 123.0
    assert obs.enabled()  # the caller's telemetry resumed too


# ---------------------------------------------------------------------------
# 8. flagship: fleet under load — tail retention + SIGKILL bundle
# ---------------------------------------------------------------------------

def _save_linear_ckpt(tmpdir):
    prefix = os.path.join(str(tmpdir), "lin")
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, no_bias=True, name="fc")
    save_checkpoint(prefix, 0, net,
                    {"fc_weight": nd.array(np.eye(4, dtype=np.float32))},
                    {})
    return prefix


@pytest.mark.chaos
@pytest.mark.slow
def test_flagship_tail_retention_and_flight_recorder_across_fleet(tmp_path):
    """The acceptance drive: a ProcReplica fleet under mixed load with
    head sampling LOW and tail mode ON — every deadline-exceeded
    request's cross-process trace (client→front→replica one trace_id) is
    retained, fast-path traces drop within budget, and a SIGKILL'd
    replica leaves a flight-recorder bundle whose profiler lane the fleet
    report merges into the one timeline."""
    import fleet_report as fr

    prefix = _save_linear_ckpt(tmp_path)
    obs_dir = str(tmp_path / "obs")
    obs.enable()
    context.set_sample_rate(0.01)   # head sampling would miss ~everything
    tail.enable()
    tail.buffer().policy = tail.RetentionPolicy(
        slow_ms=1e9, budget_per_s=1e9, burst=1e9, baseline=0.0)
    profile.start(hz=67)
    env = {"MXNET_SERVE_PLATFORM": "cpu", "JAX_PLATFORMS": "cpu",
           "MXNET_OBS_BLACKBOX_FLUSH_S": "0.25",
           "MXNET_OBS_TAIL_HOLD_S": "60"}
    pool = ReplicaPool.spawn(prefix, 2, env=env, obs_dir=obs_dir,
                             probe_interval=0.2, backoff_base=0.1,
                             backoff_cap=1.0, ready_timeout=180).start()
    front = None
    try:
        router = Router(pool, breaker_cooldown=0.3)
        front = FleetServer(router, port=0)
        front.start()
        addr = ("127.0.0.1", front.port)

        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        ok = deadlined = 0
        cli = ServeClient(*addr)
        for i in range(50):
            try:
                if i % 10 == 3:
                    # an impossible deadline: the interesting request the
                    # north-star regime must NEVER lose (shed at the
                    # front — its trace is client→front)
                    cli.infer(x, deadline_ms=0.0001)
                else:
                    np.testing.assert_array_equal(
                        cli.infer(x, deadline_ms=10000), x)
                    ok += 1
            except serve.DeadlineExceeded:
                deadlined += 1
            except (serve.RequestRejected, serve.Draining):
                pass
        # the "keep THIS one" escape hatch: forced roots record durably
        # on EVERY hop at once — the replica included
        with tail.forced():
            for _ in range(3):
                np.testing.assert_array_equal(
                    cli.infer(x, deadline_ms=10000), x)
        # and one request whose root the TEST owns: its spans pend on
        # every hop (client ring stays empty) until the verdict rides a
        # telemetry collection — the replica-buffer promotion path
        held_root = context.new_root()
        assert held_root.tail
        with context.use(held_root):
            for _ in range(4):   # round-robin: BOTH replicas hold spans
                np.testing.assert_array_equal(
                    cli.infer(x, deadline_ms=10000), x)
        assert deadlined >= 4 and ok >= 40

        # every deadline-exceeded request was retained, by reason
        st = tail.stats()
        assert st["retained"] >= deadlined + 3
        retained_deadline = metrics.registry.counter(
            "tail.retained.deadline").value
        assert retained_deadline == deadlined
        # the fast path dropped (uniform baseline pinned to 0 here)
        assert st["dropped"] >= ok * 0.9
        ring = [e for e in obs.trace.tracer.events()]
        retained_ids = set(tail.retained_ids())
        ring_tids = {(r[6] or {}).get("trace_id") for r in ring}
        assert ring_tids - {None} <= retained_ids | {held_root.trace_id}
        # a retained deadline trace stitches client→front on one trace_id
        by_name = {}
        for r in ring:
            if (r[6] or {}).get("trace_id"):
                by_name.setdefault(r[1], set()).add(r[6]["trace_id"])
        assert by_name.get("serve.client.rpc", set()) \
            & by_name.get("serve.rpc", set())

        # SIGKILL one replica mid-fleet; its bundle is the evidence
        kill_pid = pool.members()[0].handle.proc.pid
        time.sleep(0.6)             # ≥2 flush periods of profiler samples
        pool.kill(0)
        deadline_t = time.monotonic() + 120
        m0 = pool.members()[0]
        while time.monotonic() < deadline_t and not (
                m0.restarts >= 1 and m0.state == "ready"):
            time.sleep(0.3)

        # one collection settles the fleet: the verdict list (plus the
        # held root's id) fans out and the replicas' pending spans
        # promote into the very parts this collection returns
        tel = cli.telemetry(drain=True, retained=[held_root.trace_id])
        cli.close()
        parts = tel["parts"]
        assert len(parts) >= 2      # front + at least the survivor
        exec_tids = {
            (s.get("args") or {}).get("trace_id")
            for p in parts[1:] for s in p.get("spans") or ()
            if s.get("name") in ("serve.rpc", "serve.queue_wait",
                                 "serve.execute")}
        exec_tids.discard(None)
        assert exec_tids, "no replica-side spans were collected"
        # the fleet retains or drops a trace AS A UNIT: every replica-side
        # trace id was retained by a verdict (forced, policy, or the held
        # root's explicit resolve) — never a dropped fast-path trace
        all_retained = set(tail.retained_ids())
        assert held_root.trace_id in all_retained   # resolve logged it
        assert exec_tids <= all_retained, \
            "a replica kept spans the fleet's verdict never retained"
        # the held root's replica spans promoted WITH this collection
        assert held_root.trace_id in exec_tids
        # at least one trace has all three hops stitched
        front_tids = {
            (s.get("args") or {}).get("trace_id")
            for s in parts[0].get("spans") or ()
            if s.get("name") == "fleet.route"}
        client_tids = by_name.get("serve.client.rpc", set())
        assert exec_tids & front_tids & client_tids

        # the corpse's flight-recorder bundle survived the SIGKILL
        bundle_path = os.path.join(obs_dir,
                                   f"blackbox-{kill_pid}-last.json")
        assert os.path.exists(bundle_path), \
            f"no last bundle for killed pid {kill_pid} in {obs_dir}"
        part = fr.jsonl_to_part(bundle_path)
        assert part["pid"] == kill_pid
        prof_spans = [e for e in part["spans"]
                      if e["name"].startswith("prof:")]
        assert prof_spans, "bundle carries no profiler lane"
        # ... attributing the corpse's last seconds by phase: every lane
        # entry names a phase and carries its sampled leaf frame
        assert all(e["name"][5:] and "leaf" in (e.get("args") or {})
                   for e in prof_spans)
        merged = merge_chrome_parts(parts + [part])
        lanes = {e["pid"] for e in merged["traceEvents"]}
        assert kill_pid in lanes
        assert any(e.get("name", "").startswith("prof:")
                   for e in merged["traceEvents"]
                   if e.get("pid") == kill_pid)
        json.dumps(merged)
    finally:
        if front is not None:
            front.stop()
        pool.stop()
