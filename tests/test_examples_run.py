"""Run every baseline example script FOR REAL (VERDICT r3 item 4): tiny
config, synthetic data, CPU, 1-3 actual training steps through each
script's own main/fit path, asserting finite loss from the script's own
log output. The reference's example scripts are its de-facto acceptance
tests (reference example/image-classification/ — TBV); `--help` smoke
proved nothing when round 2's pipeline ran 42× slow.

MXNET_FORCE_PLATFORM=cpu pins the subprocess backend (the image preloads
jax with JAX_PLATFORMS=axon via sitecustomize, so plain env vars are too
late — mxnet_tpu/__init__.py applies the config.update at import).
"""
import os
import re
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CASES = [
    ("bert", "example/bert/pretrain.py",
     ["--model", "tiny", "--vocab-size", "100", "--batch-size", "2",
      "--seq-len", "16", "--steps", "2", "--mesh", "dp=1"],
     r"step \d+ loss ([\d.eE+-]+|nan|inf)"),
    ("bert_mesh8", "example/bert/pretrain.py",
     ["--model", "tiny", "--vocab-size", "100", "--batch-size", "8",
      "--seq-len", "16", "--steps", "2", "--mesh", "dp=2,sp=2,tp=2"],
     r"step \d+ loss ([\d.eE+-]+|nan|inf)"),
    ("word_lm", "example/rnn/word_lm/train.py",
     ["--emsize", "16", "--nhid", "16", "--nlayers", "1", "--epochs", "1",
      "--batch-size", "4", "--bptt", "8", "--max-batches", "2",
      "--vocab-size", "50"],
     r"epoch \d+ done: loss ([\d.eE+-]+|nan|inf)"),
    ("transformer", "example/transformer/train.py",
     ["--units", "32", "--hidden", "64", "--layers", "1", "--heads", "2",
      "--vocab-size", "100", "--batch-size", "2", "--seq-len", "16",
      "--steps", "2"],
     r"step \d+ loss ([\d.eE+-]+|nan|inf)"),
    ("ssd", "example/ssd/train.py",
     ["--num-classes", "3", "--batch-size", "2", "--image-size", "64",
      "--steps", "2"],
     r"step \d+ loss ([\d.eE+-]+|nan|inf)"),
    ("imagenet_module", "example/image-classification/train_imagenet.py",
     ["--network", "resnet", "--num-layers", "18", "--batch-size", "2",
      "--max-batches", "2", "--image-shape", "3,32,32",
      "--num-epochs", "1"],
     r"Train-accuracy=([\d.eE+-]+|nan)"),
]


@pytest.mark.parametrize("name,script,args,loss_re",
                         CASES, ids=[c[0] for c in CASES])
def test_example_trains_a_step(name, script, args, loss_re):
    env = dict(os.environ)
    env["MXNET_FORCE_PLATFORM"] = "cpu"
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, script)] + args,
        capture_output=True, text=True, timeout=560, env=env, cwd="/")
    assert r.returncode == 0, f"{script} rc={r.returncode}:\n{r.stderr[-2000:]}"
    text = r.stdout + r.stderr
    matches = re.findall(loss_re, text)
    assert matches, (f"{script}: no loss line matching {loss_re!r} in "
                     f"output:\n{text[-2000:]}")
    val = float(matches[-1])
    import math

    assert math.isfinite(val), f"{script}: non-finite loss {val}"
