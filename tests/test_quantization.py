"""INT8 quantization ops + gluon quantize_net (reference
src/operator/quantization/* and contrib/quantization.py — TBV; round 2 had
a raise-only stub here)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.ops.registry import get_op


def _fn(name):
    return get_op(name).fn


def test_quantize_dequantize_roundtrip():
    rng = np.random.RandomState(0)
    x = jnp.asarray((rng.rand(64) * 10 - 5).astype(np.float32))
    q, mn, mx_ = _fn("_contrib_quantize")(x, jnp.float32(-5).reshape(1),
                                          jnp.float32(5).reshape(1))
    assert q.dtype == jnp.int8
    back = _fn("_contrib_dequantize")(q, mn, mx_)
    # max error is half a quantization step (5/127)
    assert float(jnp.abs(back - x).max()) <= 5 / 127 * 0.5 + 1e-6


def test_quantize_v2_online_range():
    x = jnp.asarray(np.array([-2.0, 0.0, 4.0], np.float32))
    q, mn, mx_ = _fn("_contrib_quantize_v2")(x)
    assert float(mx_[0]) == pytest.approx(4.0)
    np.testing.assert_array_equal(np.asarray(q), [-64, 0, 127])


def test_quantized_fc_close_to_f32():
    rng = np.random.RandomState(1)
    x = (rng.rand(4, 16) - 0.5).astype(np.float32)
    w = (rng.rand(8, 16) - 0.5).astype(np.float32)
    ref = x @ w.T
    qx, mn_d, mx_d = _fn("_contrib_quantize_v2")(jnp.asarray(x))
    qw, mn_w, mx_w = _fn("_contrib_quantize_v2")(jnp.asarray(w))
    acc, mn_o, mx_o = _fn("_contrib_quantized_fully_connected")(
        qx, qw, None, mn_d, mx_d, mn_w, mx_w, no_bias=True, num_hidden=8)
    assert acc.dtype == jnp.int32
    out = _fn("_contrib_dequantize")(acc, mn_o, mx_o)
    # int8 quantization noise: elementwise tolerance ~ step_x*|w|+step_w*|x|
    assert float(np.abs(np.asarray(out) - ref).max()) < 0.1
    assert np.corrcoef(np.asarray(out).ravel(), ref.ravel())[0, 1] > 0.999


def test_quantized_conv_close_to_f32():
    rng = np.random.RandomState(2)
    x = (rng.rand(2, 3, 8, 8) - 0.5).astype(np.float32)
    w = (rng.rand(4, 3, 3, 3) - 0.5).astype(np.float32)
    ref = np.asarray(jax.lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    qx, mn_d, mx_d = _fn("_contrib_quantize_v2")(jnp.asarray(x))
    qw, mn_w, mx_w = _fn("_contrib_quantize_v2")(jnp.asarray(w))
    acc, mn_o, mx_o = _fn("_contrib_quantized_conv")(
        qx, qw, None, mn_d, mx_d, mn_w, mx_w, kernel=(3, 3), pad=(1, 1),
        num_filter=4, no_bias=True)
    out = _fn("_contrib_dequantize")(acc, mn_o, mx_o)
    assert np.corrcoef(np.asarray(out).ravel(), ref.ravel())[0, 1] > 0.999


def test_requantize_and_pooling_flatten():
    rng = np.random.RandomState(3)
    x = (rng.rand(2, 2, 4, 4) - 0.5).astype(np.float32)
    qx, mn, mx_ = _fn("_contrib_quantize_v2")(jnp.asarray(x))
    p, pmn, pmx = _fn("_contrib_quantized_pooling")(qx, mn, mx_,
                                                    kernel=(2, 2),
                                                    stride=(2, 2))
    assert p.shape == (2, 2, 2, 2) and p.dtype == qx.dtype
    f, _, _ = _fn("_contrib_quantized_flatten")(p, pmn, pmx)
    assert f.shape == (2, 8)
    # requantize an int32 accumulator back to int8
    acc = jnp.asarray(rng.randint(-1000, 1000, (8,)).astype(np.int32))
    scale = jnp.float32(1000 / (2.0 ** 31 - 1))
    q8, qmn, qmx = _fn("_contrib_requantize")(
        acc, (-(scale * (2.0 ** 31 - 1))).reshape(1),
        (scale * (2.0 ** 31 - 1)).reshape(1))
    assert q8.dtype == jnp.int8


def test_quantize_net_gluon():
    rng = np.random.RandomState(4)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, in_units=8))
        net.add(nn.Activation("relu"))
        net.add(nn.Dense(4, in_units=16))
    net.initialize()
    calib = [nd.array((rng.rand(8, 8) - 0.5).astype(np.float32))
             for _ in range(4)]
    x = nd.array((rng.rand(8, 8) - 0.5).astype(np.float32))
    ref = net(x).asnumpy()

    from mxnet_tpu.contrib.quantization import quantize_net

    qnet = quantize_net(net, calib_data=calib, calib_mode="naive")
    assert len(qnet._quantized_layers) == 2
    out = qnet(x).asnumpy()
    assert out.shape == ref.shape
    # int8 path tracks the f32 reference closely on calibrated data
    denom = np.abs(ref).max() or 1.0
    assert np.abs(out - ref).max() / denom < 0.05
    assert np.corrcoef(out.ravel(), ref.ravel())[0, 1] > 0.99


def test_quantize_net_validation():
    from mxnet_tpu.contrib.quantization import quantize_net

    net = nn.Dense(2, in_units=3)
    net.initialize()
    with pytest.raises(ValueError):
        quantize_net(net, calib_mode="naive")  # no calib data
    # the recognized-but-unimplemented mode is a structured
    # NotImplementedError naming the gap, not a generic ValueError
    with pytest.raises(NotImplementedError, match="ROADMAP item 5"):
        quantize_net(net, calib_mode="entropy")
    with pytest.raises(ValueError, match="naive"):
        quantize_net(net, calib_mode="bogus")


def test_calib_mode_error_paths_unified():
    """quantize_net and quantize_model raise the SAME structured errors:
    entropy → NotImplementedError naming the supported modes + the tracked
    gap; anything else → ValueError listing the supported modes (the two
    entry points used to disagree on both the type and the list)."""
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.contrib.quantization import (SUPPORTED_CALIB_MODES,
                                                quantize_model, quantize_net)

    net = nn.Dense(2, in_units=3)
    net.initialize()
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=2, name="fc")
    arg = {"fc_weight": np.ones((2, 3), np.float32),
           "fc_bias": np.zeros(2, np.float32)}

    for entry in (lambda m: quantize_net(net, calib_mode=m),
                  lambda m: quantize_model(fc, arg, calib_mode=m)):
        with pytest.raises(NotImplementedError) as ei:
            entry("entropy")
        for mode in SUPPORTED_CALIB_MODES:
            assert mode in str(ei.value)
        assert "ROADMAP item 5" in str(ei.value)
        with pytest.raises(ValueError) as ei:
            entry("minmax2")
        for mode in SUPPORTED_CALIB_MODES:
            assert mode in str(ei.value)


def test_quantize_net_calib_none_and_checkpoint():
    """calib_mode='none' quantizes with runtime ranges; checkpoints keep the
    original f32 weights so an unquantized twin can load them."""
    rng = np.random.RandomState(5)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4))
        net.add(nn.Dense(2, in_units=8))
    net.initialize()
    x = nd.array((rng.rand(4, 4) - 0.5).astype(np.float32))
    ref = net(x).asnumpy()
    w_before = {k: p.data().asnumpy()
                for k, p in net._collect_params_with_prefix().items()}

    from mxnet_tpu.contrib.quantization import quantize_net

    qnet = quantize_net(net, calib_mode="none")
    assert len(qnet._quantized_layers) == 2
    out = qnet(x).asnumpy()
    assert np.corrcoef(out.ravel(), ref.ravel())[0, 1] > 0.99
    # f32 params still reachable for save/load
    after = qnet._collect_params_with_prefix() if hasattr(
        qnet, "_collect_params_with_prefix") else {}
    assert set(after) == set(w_before)
    import tempfile, os

    f = os.path.join(tempfile.mkdtemp(), "q.params")
    qnet.save_parameters(f)
    fresh = nn.HybridSequential()
    with fresh.name_scope():
        fresh.add(nn.Dense(8, in_units=4))
        fresh.add(nn.Dense(2, in_units=8))
    fresh.load_parameters(f)
    np.testing.assert_allclose(fresh(x).asnumpy(), ref, rtol=1e-6)


def test_quantize_net_activation_flatten_and_root():
    """r3 review findings: activation preserved, flatten=False supported,
    root-Dense quantizable, silent-no-op warns."""
    rng = np.random.RandomState(6)
    # activation preserved
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.initialize()
    x = nd.array((rng.rand(4, 4) - 0.5).astype(np.float32))
    ref = net(x).asnumpy()
    assert (ref >= 0).all()
    from mxnet_tpu.contrib.quantization import quantize_net

    out = quantize_net(net, calib_mode="none")(x).asnumpy()
    assert (out >= 0).all(), "activation dropped by quantization"

    # flatten=False on 3D input
    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(8, in_units=4, flatten=False))
    net2.initialize()
    x3 = nd.array((rng.rand(2, 3, 4) - 0.5).astype(np.float32))
    ref2 = net2(x3).asnumpy()
    out2 = quantize_net(net2, calib_mode="none")(x3).asnumpy()
    assert out2.shape == ref2.shape == (2, 3, 8)
    assert np.corrcoef(out2.ravel(), ref2.ravel())[0, 1] > 0.99

    # root Dense
    root = nn.Dense(2, in_units=3)
    root.initialize()
    xr = nd.array(rng.rand(2, 3).astype(np.float32))
    refr = root(xr).asnumpy()
    q = quantize_net(root, calib_mode="none")
    assert q._quantized_layers
    outr = q(xr).asnumpy()
    assert np.corrcoef(outr.ravel(), refr.ravel())[0, 1] > 0.99

    # silent no-op warns (hybridized net, naive calibration)
    import warnings as w

    net3 = nn.HybridSequential()
    with net3.name_scope():
        net3.add(nn.Dense(4, in_units=4))
    net3.initialize()
    net3.hybridize()
    net3(x)
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        quantize_net(net3, calib_data=[x], calib_mode="naive")
    assert any("no Dense layer was quantized" in str(r.message) for r in rec)


def test_quantize_model_symbolic_fc():
    """Reference symbolic entry point: quantize_model on an MLP rewrites FC
    nodes into quantize_v2 -> int8 FC -> dequantize and matches f32."""
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.contrib.quantization import quantize_model
    from mxnet_tpu.io import NDArrayIter

    rng = np.random.RandomState(0)
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")

    args = {
        "fc1_weight": nd.array(rng.uniform(-0.5, 0.5, (16, 8)).astype(np.float32)),
        "fc1_bias": nd.array(rng.uniform(-0.1, 0.1, (16,)).astype(np.float32)),
        "fc2_weight": nd.array(rng.uniform(-0.5, 0.5, (4, 16)).astype(np.float32)),
        "fc2_bias": nd.array(rng.uniform(-0.1, 0.1, (4,)).astype(np.float32)),
    }
    x = rng.uniform(0, 1, (32, 8)).astype(np.float32)
    calib = NDArrayIter(x, batch_size=8)

    qsym, qargs, qaux = quantize_model(
        net, args, {}, calib_mode="naive", calib_data=calib,
        data_names=("data",))
    assert "fc1_weight_quantize" in qargs
    assert qargs["fc1_weight_quantize"].dtype == np.int8
    assert "fc1_weight" not in qargs
    ops = [n._op for n in qsym._base()._topo() if n._op]
    assert "_contrib_quantize_v2" in ops
    assert "_contrib_quantized_fully_connected" in ops
    assert "_contrib_dequantize" in ops

    ref = net.eval(data=nd.array(x), **args)
    out = qsym.eval(data=nd.array(x), **qargs)
    ref0 = ref[0].asnumpy() if isinstance(ref, (list, tuple)) else ref.asnumpy()
    out0 = out[0].asnumpy() if isinstance(out, (list, tuple)) else out.asnumpy()
    scale = np.abs(ref0).max()
    assert np.abs(out0 - ref0).max() / scale < 0.05, \
        f"int8 output deviates {np.abs(out0 - ref0).max() / scale:.3f}"


def test_quantize_model_symbolic_conv():
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.contrib.quantization import quantize_model

    rng = np.random.RandomState(1)
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1),
                          name="conv1")
    net = sym.Activation(net, act_type="relu", name="reluc")
    args = {
        "conv1_weight": nd.array(rng.uniform(-0.3, 0.3, (4, 3, 3, 3)).astype(np.float32)),
        "conv1_bias": nd.array(rng.uniform(-0.1, 0.1, (4,)).astype(np.float32)),
    }
    x = rng.uniform(0, 1, (2, 3, 8, 8)).astype(np.float32)
    qsym, qargs, _ = quantize_model(net, args, {}, calib_mode="none")
    ref = net.eval(data=nd.array(x), **args)
    out = qsym.eval(data=nd.array(x), **qargs)
    ref0 = ref[0].asnumpy() if isinstance(ref, (list, tuple)) else ref.asnumpy()
    out0 = out[0].asnumpy() if isinstance(out, (list, tuple)) else out.asnumpy()
    scale = np.abs(ref0).max()
    assert np.abs(out0 - ref0).max() / scale < 0.05


def test_quantize_model_excluded_and_graph():
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.contrib.quantization import quantize_graph, quantize_model

    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fcx", no_bias=True)
    args = {"fcx_weight": nd.array(np.eye(4, 6, dtype=np.float32))}
    qsym, qargs, _ = quantize_model(net, args, {}, calib_mode="none",
                                    excluded_sym_names=["fcx"])
    assert [n._op for n in qsym._base()._topo() if n._op] == ["FullyConnected"]
    gsym, gargs, _, collector = quantize_graph(net, args, {})
    assert collector is None
    assert "fcx_weight_quantize" in gargs


def test_quantize_model_tied_weights():
    """A weight shared by two FC nodes quantizes once and both layers
    produce real (non-zero) int8 outputs."""
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.contrib.quantization import quantize_model

    rng = np.random.RandomState(2)
    w_shared = nd.array(rng.uniform(-0.5, 0.5, (8, 8)).astype(np.float32))
    data = sym.Variable("data")
    wvar = sym.Variable("shared_weight")
    h = sym.FullyConnected(data, wvar, num_hidden=8, no_bias=True,
                           name="fca")
    out = sym.FullyConnected(h, wvar, num_hidden=8, no_bias=True,
                             name="fcb")
    args = {"shared_weight": w_shared}
    x = rng.uniform(0, 1, (4, 8)).astype(np.float32)
    qsym, qargs, _ = quantize_model(out, args, {}, calib_mode="none")
    assert "shared_weight_quantize" in qargs
    assert "shared_weight" not in qargs  # fully consumed
    ref = out.eval(data=nd.array(x), **args)
    got = qsym.eval(data=nd.array(x), **qargs)
    ref0 = ref[0].asnumpy() if isinstance(ref, (list, tuple)) else ref.asnumpy()
    got0 = got[0].asnumpy() if isinstance(got, (list, tuple)) else got.asnumpy()
    assert np.abs(got0).max() > 0, "tied-weight int8 graph went silent zero"
    scale = np.abs(ref0).max()
    assert np.abs(got0 - ref0).max() / scale < 0.08


def test_quantize_graph_honors_calib_mode():
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.contrib.quantization import quantize_graph

    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fcg", no_bias=True)
    args = {"fcg_weight": nd.array(np.eye(4, 6, dtype=np.float32))}
    with pytest.raises(NotImplementedError):
        quantize_graph(net, args, {}, calib_mode="entropy")
    with pytest.raises(ValueError):
        quantize_graph(net, args, {}, calib_mode="naive")  # no calib_data


def test_quantize_model_ragged_final_calib_batch():
    """naive calibration must tolerate a final batch smaller than the bind
    batch (num_calib_examples not a multiple of batch_size) — the ragged
    batch gets its own bind instead of a shape-mismatch crash, and its
    values still widen the ranges (ADVICE.md)."""
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.contrib.quantization import quantize_model

    rng = np.random.RandomState(5)
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fcr")
    args = {
        "fcr_weight": nd.array(rng.uniform(-0.5, 0.5, (4, 6)).astype(np.float32)),
        "fcr_bias": nd.array(np.zeros(4, np.float32)),
    }

    class _Ragged:
        """8, 8, 3 — the last batch is ragged; the extreme value lives
        ONLY there, so skipping it would visibly narrow the range."""

        def __iter__(self):
            x1 = rng.uniform(0, 1, (8, 6)).astype(np.float32)
            x2 = rng.uniform(0, 1, (8, 6)).astype(np.float32)
            x3 = rng.uniform(0, 1, (3, 6)).astype(np.float32)
            x3[0, 0] = 7.5
            return iter([nd.array(x1), nd.array(x2), nd.array(x3)])

    qsym, qargs, _ = quantize_model(
        net, args, {}, calib_mode="naive", calib_data=_Ragged(),
        data_names=("data",))
    # the calibrated max on the data input must come from the ragged batch
    attrs = {n._name: n._attrs for n in qsym._base()._topo()
             if n._op == "_contrib_quantize_v2"}
    assert attrs, "no calibrated quantize_v2 node"
    (a,) = attrs.values()
    assert float(a["max_calib_range"]) >= 7.5, \
        f"ragged final batch was dropped from calibration: {a}"


def test_quantized_artifact_serves():
    """quantize_model int8 artifacts are a first-class serve-engine input
    (ISSUE 5 satellite): the engine buckets/pads them like any graph and
    tracks the f32 reference closely."""
    import pytest as _pt

    from mxnet_tpu import symbol as sym
    from mxnet_tpu.contrib.quantization import quantize_model
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.serve import InferenceEngine

    rng = np.random.RandomState(6)
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fq1")
    net = sym.Activation(net, act_type="relu", name="relu")
    net = sym.FullyConnected(net, num_hidden=3, name="fq2")
    args = {
        "fq1_weight": nd.array(rng.uniform(-0.5, 0.5, (8, 6)).astype(np.float32)),
        "fq1_bias": nd.array(rng.uniform(-0.1, 0.1, (8,)).astype(np.float32)),
        "fq2_weight": nd.array(rng.uniform(-0.5, 0.5, (3, 8)).astype(np.float32)),
        "fq2_bias": nd.array(np.zeros(3, np.float32)),
    }
    x = rng.uniform(0, 1, (32, 6)).astype(np.float32)
    qsym, qargs, qaux = quantize_model(
        net, args, {}, calib_mode="naive",
        calib_data=NDArrayIter(x, batch_size=8), data_names=("data",))
    engine = InferenceEngine(qsym, qargs, qaux, max_batch_size=8,
                             lint="off")
    ref = net.eval(data=nd.array(x[:5]), **args)
    ref0 = (ref[0] if isinstance(ref, (list, tuple)) else ref).asnumpy()
    out = engine.predict(x[:5])  # ragged 5 -> bucket 8, pad + slice
    scale = np.abs(ref0).max()
    assert np.abs(out - ref0).max() / scale < 0.05
    assert engine.num_programs == 1
