"""Fault-injection suite (``pytest -m chaos`` / ``make chaos``).

Deterministic injectors from mxnet_tpu/chaos/ drive three proof obligations
(docs/ROBUSTNESS.md):

1. exactly-once PS mutations — dropped/duplicated RPC frames must not
   double-apply gradients (dense AND sparse) or double-enter barriers;
2. SIGKILL at an arbitrary step + ``resume="auto"`` reproduces the
   uninterrupted run's final params bitwise on CPU (flagship, subprocess);
3. a checkpoint writer killed mid-commit leaves only ignorable garbage
   (see also the CRC fallback tests in test_checkpoint.py).

Subprocess tests are additionally marked ``slow`` (tier-1 excludes slow);
the in-process RPC tests are fast and ride in tier-1 too.
"""
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

from mxnet_tpu.chaos import rpc as chaos_rpc
from mxnet_tpu.chaos.proc import run_to_completion, run_until_step

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHAOS_KILL_TOOL = os.path.join(REPO, "tools", "chaos_kill.py")


@pytest.fixture
def ps_pair():
    """A started PSServer + connected PSClient; chaos rules cleared around
    each test so injected faults can't leak."""
    from mxnet_tpu.kvstore.ps_client import PSClient
    from mxnet_tpu.kvstore.ps_server import PSServer

    chaos_rpc.reset()
    srv = PSServer(host="127.0.0.1", port=0, num_workers=1)
    srv.start()
    cli = PSClient("127.0.0.1", srv.port, timeout=5, retries=6,
                   retry_interval=0.05)
    yield srv, cli
    chaos_rpc.reset()
    srv.stop()


# ---------------------------------------------------------------------------
# exactly-once pushes under injected connection faults (satellite)
# ---------------------------------------------------------------------------

def test_push_exactly_once_under_dropped_reply(ps_pair):
    """Drop the PUSH_SEQ reply: the server HAS applied the gradient, the
    client retries — the (client_id, seq) dedup must keep it applied exactly
    once (w == 1, not 2)."""
    srv, cli = ps_pair
    cli.init("w", np.zeros(4, np.float32))
    chaos_rpc.configure([chaos_rpc.Rule("push_seq", "drop_reply", {1})])
    cli.push("w", np.ones(4, np.float32))
    chaos_rpc.reset()
    np.testing.assert_array_equal(cli.pull("w"), np.ones(4, np.float32))


def test_push_exactly_once_under_dropped_request(ps_pair):
    """Drop the request instead: the server never saw attempt 1, so the
    retry is the first application — still exactly once."""
    srv, cli = ps_pair
    cli.init("w", np.zeros(4, np.float32))
    chaos_rpc.configure([chaos_rpc.Rule("push_seq", "drop_request", {1})])
    cli.push("w", np.ones(4, np.float32))
    chaos_rpc.reset()
    np.testing.assert_array_equal(cli.pull("w"), np.ones(4, np.float32))


def test_push_exactly_once_under_duplicated_frame(ps_pair):
    """A duplicating network sends the same frame twice back-to-back; the
    second copy carries the same seq and must be acked without re-applying."""
    srv, cli = ps_pair
    cli.init("w", np.zeros(4, np.float32))
    chaos_rpc.configure([chaos_rpc.Rule("push_seq", "dup", {1})])
    cli.push("w", np.ones(4, np.float32))
    chaos_rpc.reset()
    np.testing.assert_array_equal(cli.pull("w"), np.ones(4, np.float32))


def test_sparse_push_exactly_once_under_dropped_reply(ps_pair):
    """The sparse path (PUSH_SPARSE_SEQ) carries the same (client_id, seq)
    dedup: a retried row update lands exactly once."""
    srv, cli = ps_pair
    cli.init("emb", np.zeros((5, 3), np.float32))
    chaos_rpc.configure([chaos_rpc.Rule("push_sparse_seq", "drop_reply", {1})])
    cli.push_row_sparse("emb", np.array([1, 3], np.int32),
                        np.ones((2, 3), np.float32))
    chaos_rpc.reset()
    out = cli.pull("emb")
    expect = np.zeros((5, 3), np.float32)
    expect[[1, 3]] = 1.0
    np.testing.assert_array_equal(out, expect)


def test_sparse_push_exactly_once_under_duplicated_frame(ps_pair):
    srv, cli = ps_pair
    cli.init("emb", np.zeros((4, 2), np.float32))
    chaos_rpc.configure([chaos_rpc.Rule("push_sparse_seq", "dup", {1})])
    cli.push_row_sparse("emb", np.array([0, 0], np.int32),
                        np.full((2, 2), 2.0, np.float32))
    chaos_rpc.reset()
    # duplicate indices within ONE push still accumulate (np.add.at), but
    # the duplicated FRAME must not double that
    expect = np.zeros((4, 2), np.float32)
    expect[0] = 4.0
    np.testing.assert_array_equal(cli.pull("emb"), expect)


def test_interleaved_drops_converge_to_exact_sum(ps_pair):
    """A lossy session: several pushes with replies dropped at assorted
    occurrences — the final weight equals the exact sum of all gradients."""
    srv, cli = ps_pair
    cli.init("w", np.zeros(3, np.float32))
    chaos_rpc.configure([
        chaos_rpc.Rule("push_seq", "drop_reply", {2, 5}),
        chaos_rpc.Rule("push_seq", "drop_request", {7}),
    ])
    total = np.zeros(3, np.float32)
    for i in range(1, 6):
        g = np.full(3, float(i), np.float32)
        cli.push("w", g)
        total += g
    chaos_rpc.reset()
    np.testing.assert_array_equal(cli.pull("w"), total)


# ---------------------------------------------------------------------------
# idempotent barrier (satellite)
# ---------------------------------------------------------------------------

def test_barrier_idempotent_under_dropped_reply(ps_pair):
    """A lost barrier ack triggers a retry carrying the same epoch token;
    the server re-acks from its released set instead of double-entering.
    The follow-up barrier would hang (count leak) if the retry had been
    counted as a second arrival."""
    srv, cli = ps_pair
    chaos_rpc.configure([chaos_rpc.Rule("barrier", "drop_reply", {1})])
    cli.barrier(timeout=10.0)
    chaos_rpc.reset()
    cli.barrier(timeout=10.0)  # next round must still work
    assert srv._barrier_count == 0


def test_barrier_two_workers_with_lost_replies(ps_pair):
    """Both workers lose their first barrier ack; both retries must be
    deduped and round 2 must complete inside the straggler window."""
    from mxnet_tpu.kvstore.ps_client import PSClient
    from mxnet_tpu.kvstore.ps_server import PSServer

    srv = PSServer(host="127.0.0.1", port=0, num_workers=2,
                   barrier_timeout=15.0)
    srv.start()
    clients = [PSClient("127.0.0.1", srv.port, timeout=5, retries=6,
                        retry_interval=0.05) for _ in range(2)]
    # rules are process-wide; occurrence counters are thread-local, so each
    # worker thread drops ITS first reply
    chaos_rpc.configure([chaos_rpc.Rule("barrier", "drop_reply", {1})])
    errs = []

    def _rounds(cli):
        try:
            cli.barrier(timeout=20.0)
            cli.barrier(timeout=20.0)
        except Exception as e:  # surfaced below
            errs.append(e)

    threads = [threading.Thread(target=_rounds, args=(c,)) for c in clients]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    chaos_rpc.reset()
    srv.stop()
    assert not errs, errs
    assert srv._barrier_count == 0


def test_rpc_rules_count_occurrences_independently():
    """Two rules targeting the same (op, action) at different occurrences
    must each see every matching event once — a shared counter would make
    occurrence specs drift (the determinism contract)."""
    from mxnet_tpu.kvstore.ps_server import OP_PUSH

    chaos_rpc.configure([chaos_rpc.Rule("push", "dup", {1}),
                         chaos_rpc.Rule("push", "dup", {3})])
    try:
        verdicts = [chaos_rpc.on_send(OP_PUSH, "k") for _ in range(4)]
        assert verdicts == ["dup", None, "dup", None]
    finally:
        chaos_rpc.reset()


# ---------------------------------------------------------------------------
# kill points (process-level injection)
# ---------------------------------------------------------------------------

def test_kill_point_sigkills_at_occurrence():
    code = (
        "from mxnet_tpu.chaos.proc import kill_point\n"
        "for i in range(5):\n"
        "    kill_point('loop')\n"
        "    print('survived', i, flush=True)\n"
        "print('done', flush=True)\n")
    env = dict(os.environ)
    env["MXNET_CHAOS_KILL"] = "loop@3"
    out = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, timeout=120)
    assert out.returncode == -signal.SIGKILL
    assert "survived 1" in out.stdout and "survived 2" not in out.stdout


def test_kill_point_noop_when_unset():
    from mxnet_tpu.chaos.proc import kill_point, reset_kill_points

    old = os.environ.pop("MXNET_CHAOS_KILL", None)
    reset_kill_points()
    try:
        for _ in range(3):
            kill_point("anything")  # must be a cheap no-op
    finally:
        if old is not None:
            os.environ["MXNET_CHAOS_KILL"] = old
        reset_kill_points()


# ---------------------------------------------------------------------------
# flagship: SIGKILL mid-training, resume, bitwise identity (subprocess)
# ---------------------------------------------------------------------------

def _orchestrate(tmp_path, kill_at_step, chaos_kill=""):
    cmd = [sys.executable, CHAOS_KILL_TOOL,
           "--kill-at-step", str(kill_at_step),
           "--ckpt-dir", str(tmp_path)]
    if chaos_kill:
        cmd += ["--chaos-kill", chaos_kill]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(cmd, env=env, cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True, timeout=540)
    return out.returncode, out.stdout


@pytest.mark.slow
def test_sigkill_mid_epoch_resume_bitwise(tmp_path):
    """Acceptance flagship: SIGKILL at an arbitrary mid-epoch step, restart
    with resume='auto', final params bitwise-identical to an uninterrupted
    run (CPU, fixed seeds)."""
    rc, out = _orchestrate(tmp_path, kill_at_step=7)
    assert rc == 0 and "BITWISE MATCH" in out, out[-3000:]


@pytest.mark.slow
def test_sigkill_writer_mid_rename_resume_bitwise(tmp_path):
    """Kill the checkpoint writer mid-commit (ckpt:pre_rename kill point) on
    top of the step kill: the torn commit must be invisible and the run
    still resumes bitwise from the previous valid checkpoint."""
    rc, out = _orchestrate(tmp_path, kill_at_step=9,
                           chaos_kill="ckpt:pre_rename@2")
    assert rc == 0 and "BITWISE MATCH" in out, out[-3000:]


@pytest.mark.slow
def test_sigkill_before_first_checkpoint_resume_bitwise(tmp_path):
    """Killed before anything committed: resume='auto' finds nothing and
    restarts from scratch — still bitwise (determinism is end-to-end)."""
    rc, out = _orchestrate(tmp_path, kill_at_step=1)
    assert rc == 0 and "BITWISE MATCH" in out, out[-3000:]
