"""Device-plane observability suite (docs/OBSERVABILITY.md "Device plane").

Covers the tentpole contracts of the device plane:

1. **Cost accounting** — every compiled program logs XLA flops +
   bytes-accessed + peak-HBM in its engine's ``compile_log``: the fused
   update engine, the serve InferenceEngine, the Executor forward AND
   backward jit sites, and CachedOp — all on CPU (the analyses are
   backend-independent).
2. **MFU/roofline attribution** — a 2-batch resnet ``Module.fit`` produces
   a chrome trace whose device spans carry ``analytic_mfu`` / ``roofline``
   attrs, a ``device.live_bytes`` counter track, and ``device.compile``
   events that ``tools/trace_report.py`` renders as counter-track and
   top-programs tables.
3. **Leak detection** — the steady-state detector flags a deliberately
   retained array list and stays quiet over a 20-step steady-state fit
   (the ``pytest -m perf`` memory gate).
4. **Regression dossier** — classification unit tests on synthetic
   trajectories (improvement / regression / gap / within-noise) and the
   real BENCH_r01..r05 acceptance: the bf16-piped inversion is flagged,
   r05 is a platform gap (never a 100% regression), and the exit code
   distinguishes regression / clean / gap.
5. **Profiler window guards** — double ``start_trace``/``stop_trace`` are
   idempotent and land as tagged obs events in the span timeline.
"""
import json
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, obs, profiler
from mxnet_tpu import symbol as sym
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import Module
from mxnet_tpu.obs import device as obs_device
from mxnet_tpu.obs import regress

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

COST_KEYS = ("flops", "bytes_accessed", "peak_hbm_bytes")


@pytest.fixture(autouse=True)
def _obs_clean():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


@pytest.fixture
def obs_on(_obs_clean):
    obs.enable()
    yield


def _tiny_resnet(num_classes=2):
    data = sym.Variable("data")
    body = sym.Convolution(data, num_filter=4, kernel=(3, 3), stride=(1, 1),
                           pad=(1, 1), no_bias=True, name="conv0")
    bn1 = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=0.9,
                        name="bn1")
    act1 = sym.Activation(bn1, act_type="relu", name="relu1")
    conv1 = sym.Convolution(act1, num_filter=4, kernel=(3, 3), stride=(1, 1),
                            pad=(1, 1), no_bias=True, name="conv1")
    body = conv1 + body
    pool = sym.Pooling(body, global_pool=True, kernel=(8, 8),
                       pool_type="avg", name="pool1")
    flat = sym.Flatten(pool, name="flatten")
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")


def _mlp_symbol(num_classes=2):
    x = sym.Variable("data")
    h = sym.FullyConnected(x, num_hidden=8, name="fc1")
    h = sym.Activation(h, act_type="relu", name="relu1")
    out = sym.FullyConnected(h, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(out, name="softmax")


def _assert_cost_fields(entry, where):
    for k in COST_KEYS:
        assert k in entry, f"{where}: compile_log entry missing {k!r}"
        assert isinstance(entry[k], int), f"{where}: {k} not an int"
    assert entry["flops"] > 0, f"{where}: zero flops"
    assert entry["bytes_accessed"] > 0, f"{where}: zero bytes_accessed"
    assert entry["peak_hbm_bytes"] > 0, f"{where}: zero peak_hbm_bytes"


# ---------------------------------------------------------------------------
# 1. cost accounting at every compile choke point (CPU)
# ---------------------------------------------------------------------------

def test_fused_engine_compile_log_carries_device_cost(obs_on):
    from mxnet_tpu.ndarray import NDArray
    from mxnet_tpu.optimizer import create
    from mxnet_tpu.optimizer.fused import FusedUpdateEngine

    eng = FusedUpdateEngine(create("sgd", learning_rate=0.1))
    w = NDArray(np.ones((16, 8), np.float32))
    g = NDArray(np.full((16, 8), 0.5, np.float32))
    eng.apply([0], [w], [g], [None])
    eng.apply([0], [w], [g], [None])
    assert len(eng.compile_log) == 1  # steady state: no retrace
    _assert_cost_fields(eng.compile_log[0], "fused")
    # the cost registry mirrors the record for attribution + bench.py
    assert obs_device.cost_of("update", "SGD")["flops"] > 0
    # execute spans carry analytic attribution (the compile call doesn't)
    execs = [e for e in obs.trace.events()
             if e[1] == "update.fused" and not e[6]["compile"]]
    assert execs and "analytic_mfu" in execs[0][6]
    assert execs[0][6]["roofline"] in ("compute", "bandwidth")


def test_executor_forward_backward_compile_log(obs_on):
    from mxnet_tpu.executor import Executor

    net = _mlp_symbol()
    ex = Executor(net, shapes={"data": (4, 6), "softmax_label": (4,)},
                  grad_req="write")
    ex.forward(is_train=True, data=np.ones((4, 6), np.float32))
    ex.backward()
    sites = {e["site"] for e in ex.compile_log}
    assert sites == {"forward", "backward"}
    for entry in ex.compile_log:
        _assert_cost_fields(entry, f"executor/{entry['site']}")
    # same-signature re-execution must not add compile_log entries
    ex.forward(is_train=True, data=np.ones((4, 6), np.float32))
    ex.backward()
    assert len(ex.compile_log) == 2


def test_serve_engine_compile_log_and_bitwise_with_capture(obs_on):
    from mxnet_tpu.serve import InferenceEngine

    net = _mlp_symbol()
    rng = np.random.RandomState(3)
    arg_params = {
        "fc1_weight": rng.randn(8, 6).astype(np.float32),
        "fc1_bias": np.zeros(8, np.float32),
        "fc2_weight": rng.randn(2, 8).astype(np.float32),
        "fc2_bias": np.zeros(2, np.float32),
    }
    engine = InferenceEngine(net, arg_params, data_names=["data"],
                             max_batch_size=4, lint="off")
    x = rng.randn(3, 6).astype(np.float32)
    out1 = engine.predict(x)
    out2 = engine.predict(x)  # steady state through the AOT executable
    np.testing.assert_array_equal(out1, out2)
    assert len(engine.compile_log) == 1
    _assert_cost_fields(engine.compile_log[0], "serve")
    assert engine.compile_log[0]["bucket"] == 4
    # every bucket warmup compiles with cost accounting too
    engine.warmup((6,))
    assert len(engine.compile_log) == len(engine.buckets)
    for entry in engine.compile_log:
        _assert_cost_fields(entry, "serve/warmup")


def test_cachedop_compile_log(obs_on):
    from mxnet_tpu import gluon

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"))
    net.add(gluon.nn.Dense(2))
    net.initialize()
    net.hybridize()
    x = nd.array(np.ones((2, 6), np.float32))
    net(x)
    net(x)
    log = net._cached_op.compile_log
    assert len(log) == 1
    _assert_cost_fields(log[0], "cachedop")


def test_capture_inactive_without_telemetry(_obs_clean):
    """Zero-cost-when-off: with telemetry off (and no env force) the
    executor stays on the plain jit path — no aval-signature bookkeeping,
    no compile_log entries, no AOT cache."""
    from mxnet_tpu.executor import Executor

    assert not obs_device.active()
    ex = Executor(_mlp_symbol(), shapes={"data": (2, 6),
                                         "softmax_label": (2,)},
                  grad_req="null")
    ex.forward(is_train=False, data=np.ones((2, 6), np.float32))
    assert ex.compile_log == [] and not ex._aot and not ex._seen_sigs


# ---------------------------------------------------------------------------
# 2. the flagship: 2-batch resnet fit → counter track + MFU attribution
# ---------------------------------------------------------------------------

def test_two_batch_resnet_fit_has_memory_track_and_mfu_attrs(
        tmp_path, obs_on):
    rng = np.random.RandomState(7)
    X = rng.randn(8, 3, 8, 8).astype(np.float32)
    y = rng.randint(0, 2, 8).astype(np.float32)
    it = NDArrayIter(X, y, batch_size=4)  # 2 batches/epoch
    mod = Module(_tiny_resnet(), context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05})

    trace_path = str(tmp_path / "trace.json")
    obs.export(trace_path)
    doc = json.load(open(trace_path))
    evs = doc["traceEvents"]

    # the memory counter track (Perfetto counter lane), one sample/batch
    mem = [e for e in evs if e.get("ph") == "C"
           and e["name"] == "device.live_bytes"]
    assert len(mem) >= 4, "expected a device.live_bytes sample per batch"
    assert all(e["args"]["value"] > 0 for e in mem)

    # per-phase analytic-MFU attributes on the device spans
    for span_name, phase in (("device.forward", "forward"),
                             ("device.backward", "backward"),
                             ("update.fused", "update")):
        attrs = [e.get("args") or {} for e in evs
                 if e.get("ph") == "X" and e["name"] == span_name]
        hits = [a for a in attrs if "analytic_mfu" in a]
        assert hits, f"no analytic_mfu attr on any {span_name} span"
        assert hits[0]["roofline"] in ("compute", "bandwidth")
        h = obs.metrics.registry.get(f"device.mfu.{phase}")
        assert h is not None and h.count > 0

    # device.compile events feed the top-programs table; the counter
    # track and program table render through trace_report
    import trace_report

    rep = trace_report.report(trace_path)
    tracks = {c["name"] for c in rep["counters"]}
    assert "device.live_bytes" in tracks
    assert rep["device_programs"], "no device.compile rows in the report"
    top = rep["device_programs"][0]
    assert top["flops"] > 0 and top["site"] in ("executor", "update")
    import io

    buf = io.StringIO()
    trace_report.render(rep, stream=buf)
    text = buf.getvalue()
    assert "device.live_bytes" in text
    assert "Top programs by device cost" in text

    # the merged-chrome path keeps the counter lane
    merged = trace_report.merged_chrome([trace_path])
    assert any(e.get("ph") == "C" for e in merged["traceEvents"])

    # Prometheus exposition carries the live-bytes gauge via the existing
    # telemetry plane (no new wire needed)
    from mxnet_tpu.obs.export import to_prometheus

    expo = to_prometheus(obs.metrics.snapshot())
    assert "mxnet_device_live_bytes" in expo


def test_sharded_trainer_ragged_batch_falls_back_to_jit(obs_on):
    """An AOT Compiled can't retrace: a later batch with different avals
    must fall back to the jit wrapper, not crash — capture on must never
    change training semantics."""
    import jax

    from mxnet_tpu import gluon, parallel as par

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"))
    net.add(gluon.nn.Dense(2))
    net.initialize()
    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    tr = par.ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            mesh, optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1})
    x = nd.array(np.ones((4, 6), np.float32))
    y = nd.array(np.zeros(4, np.int32))
    tr.step(x, y).asnumpy()
    assert tr.step_cost and tr.step_cost["flops"] > 0
    # ragged final batch: different leading dim → jit retrace, no crash
    x2 = nd.array(np.ones((2, 6), np.float32))
    y2 = nd.array(np.zeros(2, np.int32))
    loss = float(tr.step(x2, y2).asnumpy())
    assert np.isfinite(loss)
    # gluon forward after donated steps must still work: the capture path
    # must not delete parameter buffers device_put aliased on CPU (the
    # AOT executable applies donation where jax.jit silently skips it)
    net.hybridize()
    out = net(x2)
    assert np.isfinite(out.asnumpy()).all()


def test_fleet_report_keeps_corpse_counter_track(tmp_path):
    """A SIGKILL'd replica's JSONL evidence carries its device.live_bytes
    counter samples into the merged fleet timeline."""
    path = str(tmp_path / "replica.jsonl")
    obs.enable(jsonl=path)
    with obs.trace.span("serve.execute"):
        pass
    obs.trace.tracer.counter("device.live_bytes", 12345.0)
    obs.disable()

    import fleet_report

    part = fleet_report.jsonl_to_part(path)
    cs = [e for e in part["spans"] if e.get("ph") == "C"]
    assert cs and cs[0]["name"] == "device.live_bytes"
    assert cs[0]["args"]["value"] == 12345.0
    from mxnet_tpu.obs.export import merge_chrome_parts

    doc = merge_chrome_parts([part])
    assert any(e.get("ph") == "C" for e in doc["traceEvents"])


# ---------------------------------------------------------------------------
# 3. leak detector (the pytest -m perf memory gate)
# ---------------------------------------------------------------------------

@pytest.mark.perf
def test_leak_detector_flags_retained_arrays(obs_on):
    """A deliberately retained array list must trip the detector."""
    import jax.numpy as jnp

    det = obs_device.LeakDetector(window=8, warmup=2,
                                  threshold_bytes_per_step=1000)
    retained = []
    fired = None
    for step in range(30):
        retained.append(jnp.ones((256,), jnp.float32))  # 1 KB/step leak
        fired = fired or det.observe(obs_device.live_bytes())
    assert fired is not None, "retained arrays never flagged"
    assert fired["slope_bytes_per_step"] > 500
    del retained


@pytest.mark.perf
def test_leak_detector_quiet_over_20_step_steady_state_fit(obs_on):
    """A 20-step steady-state fit (params update in place) must not trip
    the leak detector — the gate that makes leak events actionable."""
    rng = np.random.RandomState(0)
    X = rng.randn(40, 6).astype(np.float32)
    y = rng.randint(0, 2, 40).astype(np.float32)
    it = NDArrayIter(X, y, batch_size=2)  # 20 batches/epoch
    mod = Module(_mlp_symbol(), context=mx.cpu())
    obs_device.monitor.reset()
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.01})
    assert obs_device.monitor.findings == [], (
        "steady-state fit flagged as a leak: "
        f"{obs_device.monitor.findings}")
    leak_events = [e for e in obs.trace.events()
                   if e[1] == "device.leak_suspected"]
    assert not leak_events


@pytest.mark.perf
def test_synthetic_leak_math():
    """Pure-math detector checks: flat + jitter stays quiet, a ramp fires
    once per window (cooldown), warmup growth is forgiven."""
    det = obs_device.LeakDetector(window=5, warmup=3,
                                  threshold_bytes_per_step=100)
    # warmup allocations (compile) look like a leak — must be dropped
    for v in (1000, 50000, 90000):
        assert det.observe(v) is None
    # steady state with jitter
    for v in (90000, 90010, 89990, 90005, 89995, 90000, 90008):
        assert det.observe(v) is None
    # now a 1 KB/step ramp
    fired = [det.observe(90000 + 1000 * i) for i in range(1, 11)]
    hits = [f for f in fired if f]
    assert hits, "ramp never fired"
    assert len(hits) <= 2, "cooldown failed: detector fired per-step"


# ---------------------------------------------------------------------------
# 4. regression dossier (synthetic trajectories + the committed history)
# ---------------------------------------------------------------------------

def _fake_round(tmp_path, n, value=None, extra=None, rc=0, error=None):
    parsed = {"metric": "resnet50_v1 fp32 train throughput", "value": value,
              "unit": "images/sec", "vs_baseline": None}
    if extra is not None:
        parsed["extra"] = extra
    if error:
        parsed["error"] = error
    p = tmp_path / f"BENCH_r{n:02d}.json"
    p.write_text(json.dumps({"n": n, "rc": rc, "parsed": parsed}))
    return str(p)


@pytest.mark.perf
def test_regress_classifies_improvement_regression_and_noise(tmp_path):
    paths = [
        _fake_round(tmp_path, 1, value=100.0, extra={"fp32_spread": 0.02}),
        _fake_round(tmp_path, 2, value=120.0, extra={"fp32_spread": 0.02}),
        _fake_round(tmp_path, 3, value=121.0, extra={"fp32_spread": 0.02}),
        _fake_round(tmp_path, 4, value=90.0, extra={"fp32_spread": 0.02}),
    ]
    d = regress.dossier(paths)
    t = d["gains"]["resnet50_fp32_ips"]["transitions"]
    assert [x["class"] for x in t] == ["improvement", "within_noise",
                                      "regression"]
    assert d["status"] == "regression"
    assert d["exit_code"] == regress.EXIT_REGRESSION


@pytest.mark.perf
def test_regress_within_spread_band_is_noise_not_regression(tmp_path):
    # a 6% drop inside a 10% measured spread must NOT classify as a
    # regression — the band comes from the artifact's own honesty field
    paths = [
        _fake_round(tmp_path, 1, value=100.0, extra={"fp32_spread": 0.10}),
        _fake_round(tmp_path, 2, value=94.0, extra={"fp32_spread": 0.03}),
    ]
    d = regress.dossier(paths)
    t = d["gains"]["resnet50_fp32_ips"]["transitions"]
    assert [x["class"] for x in t] == ["within_noise"]
    assert d["status"] == "clean"
    assert d["exit_code"] == regress.EXIT_CLEAN


@pytest.mark.perf
def test_regress_platform_gap_never_reads_as_regression(tmp_path):
    paths = [
        _fake_round(tmp_path, 1, value=100.0, extra={"fp32_spread": 0.02}),
        _fake_round(tmp_path, 2, rc=1,
                    error="device enumeration timed out — tunnel dead"),
        _fake_round(tmp_path, 3, value=101.0, extra={"fp32_spread": 0.02}),
    ]
    d = regress.dossier(paths)
    assert d["rounds"][1]["gap"]
    series = d["gains"]["resnet50_fp32_ips"]["series"]
    assert series[1] == {"round": 2, "gap": True}
    # the transition skips the gap and compares r1 -> r3: within noise
    t = d["gains"]["resnet50_fp32_ips"]["transitions"]
    assert len(t) == 1 and t[0]["class"] == "within_noise"
    assert t[0]["from_round"] == 1 and t[0]["to_round"] == 3
    assert d["status"] == "gap"
    assert d["exit_code"] == regress.EXIT_GAP


@pytest.mark.perf
def test_regress_flags_bf16_piped_inversion(tmp_path):
    paths = [_fake_round(
        tmp_path, 1, value=100.0,
        extra={"fp32_spread": 0.02, "resnet50_piped_ips": 170.0,
               "resnet50_piped_bf16_ips": 75.0})]
    d = regress.dossier(paths)
    checks = {a["check"] for a in d["anomalies"]}
    assert "bf16_piped_inversion" in checks
    assert d["exit_code"] == regress.EXIT_REGRESSION


@pytest.mark.perf
def test_bench_compare_cli_on_committed_trajectory(capsys):
    """The acceptance run: BENCH_r01..r05 → inversion flagged, r05 a
    platform gap, regression-class exit code."""
    import bench_compare

    arts = sorted(os.path.join(REPO, f"BENCH_r{i:02d}.json")
                  for i in range(1, 6))
    code = bench_compare.main(arts)
    out = capsys.readouterr().out
    assert code == regress.EXIT_REGRESSION
    assert "bf16_piped_inversion" in out
    assert "GAP" in out and "r05" in out
    assert "axon tunnel" in out


@pytest.mark.perf
def test_bench_compare_json_output(tmp_path, capsys):
    paths = [
        _fake_round(tmp_path, 1, value=100.0, extra={"fp32_spread": 0.02}),
        _fake_round(tmp_path, 2, value=130.0, extra={"fp32_spread": 0.02}),
    ]
    import bench_compare

    code = bench_compare.main(paths + ["--json"])
    assert code == regress.EXIT_CLEAN
    doc = json.loads(capsys.readouterr().out)
    assert doc["status"] == "clean"


# ---------------------------------------------------------------------------
# perf gate: the dispatch bound holds with cost capture ON
# ---------------------------------------------------------------------------

@pytest.mark.perf
def test_fused_dispatch_bound_holds_with_capture(obs_on):
    """The AOT capture path must not change the one-program-per-step
    dispatch guarantee (docs/PERFORMANCE.md)."""
    from mxnet_tpu.ndarray import NDArray
    from mxnet_tpu.optimizer import create
    from mxnet_tpu.optimizer.fused import FusedUpdateEngine

    eng = FusedUpdateEngine(create("sgd", learning_rate=0.1, momentum=0.9))
    ws = [NDArray(np.ones((8, 4), np.float32)) for _ in range(3)]
    gs = [NDArray(np.ones((8, 4), np.float32)) for _ in range(3)]
    sts = [NDArray(np.zeros((8, 4), np.float32)) for _ in range(3)]
    eng.apply([0, 1, 2], ws, gs, sts)  # compile
    with profiler.count_dispatches() as c:
        eng.apply([0, 1, 2], ws, gs, sts)
    assert c.compiled == 1, c.as_dict()
    assert len(eng.compile_log) == 1
    _assert_cost_fields(eng.compile_log[0], "fused/momentum")


# ---------------------------------------------------------------------------
# 5. profiler window guards
# ---------------------------------------------------------------------------

def test_profiler_double_start_stop_is_idempotent(tmp_path, obs_on):
    profiler.set_config(filename=str(tmp_path / "prof"))
    profiler.set_state("run")
    profiler.set_state("run")   # second start: guarded, no deep JAX raise
    (nd.ones((4, 4)) * 2).wait_to_read()
    profiler.set_state("stop")
    profiler.set_state("stop")  # second stop: guarded no-op
    d = profiler.dump()         # dump after stop: still fine
    assert d and os.path.isdir(d)
    names = [e[1] for e in obs.trace.events()]
    assert names.count("profiler.start_trace") == 1
    assert names.count("profiler.stop_trace") == 1


def test_profiler_context_manager_reentry(tmp_path, _obs_clean):
    with profiler.Profiler(filename=str(tmp_path / "p1")):
        with profiler.Profiler(filename=str(tmp_path / "p2")):
            (nd.ones((2, 2)) + 1).wait_to_read()
    # both exits stopped cleanly; a fresh window still works
    profiler.set_state("run")
    profiler.set_state("stop")
