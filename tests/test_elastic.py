"""Elastic-training suite (``pytest -m elastic`` / ``make elastic``).

Proof obligations (docs/ROBUSTNESS.md "Elastic training"):

1. membership: cold-start joins are active, joins after training started
   are quarantined until the next epoch boundary; K missed heartbeats
   declare a worker dead and bump the generation;
2. generation-scoped collectives: a dead rank RELEASES barriers / reduce
   rounds / epoch rendezvous over the survivors (no blanket timeout), a
   stale member's push is rejected, retries are idempotent;
3. PS durability: snapshots + the push WAL make exactly-once survive a
   server SIGKILL (seq-dedup table restored, zero lost / zero
   double-applied);
4. the flagship (slow): SIGKILL 1 of 3 ``dist_sync`` workers mid-epoch →
   survivors finish over rebalanced shards, the worker rejoins at the
   next epoch boundary from the shared checkpoint, and run-to-completion
   loss matches an uninjected run within documented tolerance.
"""
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu.chaos import rpc as chaos_rpc
from mxnet_tpu.kvstore import elastic as el
from mxnet_tpu.kvstore.elastic import ElasticState, ElasticWorkerSession

pytestmark = [pytest.mark.elastic, pytest.mark.chaos]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")


# 1s liveness window: fast enough for "released, not timed out" asserts,
# wide enough that a loaded CI box can't false-positive an ACTIVE member
# (its heartbeats fire every 0.2s)
_HB, _MISS = 0.2, 5


def _server(**kw):
    from mxnet_tpu.kvstore.ps_server import PSServer

    kw.setdefault("host", "127.0.0.1")
    kw.setdefault("port", 0)
    kw.setdefault("hb_interval", _HB)
    kw.setdefault("miss_k", _MISS)
    srv = PSServer(**kw)
    srv.start()
    return srv


def _session(srv, rank, **kw):
    kw.setdefault("hb_interval", _HB)
    return ElasticWorkerSession("127.0.0.1", srv.port, rank=rank, **kw)


# ---------------------------------------------------------------------------
# membership
# ---------------------------------------------------------------------------

def test_cold_start_joins_active_then_quarantined():
    st = ElasticState(hb_interval=0.05, miss_k=3)
    assert st.join(1, 0)[0] == "active"
    assert st.join(2, 1)[0] == "active"
    # any reduce marks the fleet as started → later joins quarantine
    st.reduce(1, "g", 0, np.zeros(1, np.float32), timeout=0.01)
    assert st.join(3, 2)[0] == "quarantined"
    st.close()


def test_missed_heartbeats_declare_dead_and_bump_generation():
    st = ElasticState(hb_interval=0.05, miss_k=2)
    st.join(1, 0)
    st.join(2, 1)
    gen0 = st.generation
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        st.heartbeat(1)  # keep member 1 alive; member 2 goes silent
        with st.cv:
            if st.members[2].state == "dead":
                break
        time.sleep(0.02)
    with st.cv:
        assert st.members[2].state == "dead"
        assert st.members[1].state == "active"
        assert st.active_count() == 1
    assert st.generation > gen0
    st.close()


def test_rejoin_quarantined_then_activated_with_recut_assignment():
    srv = _server()
    s1 = _session(srv, rank=0)
    s1.ensure_joined()
    # training started → a (re)joiner is quarantined mid-epoch
    out, n = s1.allreduce("g", np.ones(2, np.float32))
    assert n == 1
    s2 = _session(srv, rank=1)
    info2 = s2.ensure_joined()
    assert not info2.active
    got = {}
    t = threading.Thread(
        target=lambda: got.update(info=s2.await_activation(timeout=30)))
    t.start()
    time.sleep(0.2)
    info1 = s1.epoch_end(0)  # the boundary activates the joiner
    t.join(timeout=30)
    assert not t.is_alive()
    assert got["info"].active and got["info"].num_parts == 2
    assert info1.num_parts == 2 and info1.changed
    assert {info1.part_index, got["info"].part_index} == {0, 1}
    assert got["info"].generation == info1.generation  # committed generation
    s1.close()
    s2.close()
    srv.stop()


def test_stale_member_push_rejected():
    """A zombie (declared dead after missed heartbeats but still running)
    must get a structured stale rejection, not silently mix its gradient
    into the live generation."""
    srv = _server()
    s1 = _session(srv, rank=0)
    s2 = _session(srv, rank=1)
    s1.ensure_joined()
    s2.ensure_joined()
    s2._hb.stop()  # zombie: alive but silent
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        with srv._elastic.cv:
            if srv._elastic.members[s2.cid].state == "dead":
                break
        time.sleep(0.05)
    with pytest.raises(el.StaleMemberError):
        s2.allreduce("g", np.ones(2, np.float32))
    s1.close()
    srv.stop()


# ---------------------------------------------------------------------------
# generation-scoped collectives released over survivors
# ---------------------------------------------------------------------------

def test_dead_rank_releases_reduce_over_survivors():
    srv = _server()
    s1 = _session(srv, rank=0)
    s2 = _session(srv, rank=1)
    s1.ensure_joined(wait_for_expected=False)
    s2.ensure_joined(wait_for_expected=False)
    # one full round with both, so requirement is {s1, s2}
    res = {}
    for name, s, v in (("a", s1, 1.0), ("b", s2, 2.0)):
        threading.Thread(
            target=lambda s=s, name=name, v=v: res.update(
                {name: s.allreduce("g", np.full(2, v, np.float32))})
        ).start()
    deadline = time.monotonic() + 10
    while len(res) < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert np.allclose(res["a"][0], 3.0) and res["a"][1] == 2
    # kill s2 (heartbeats stop = SIGKILL to the server's eyes)
    s2._hb.stop()
    t0 = time.monotonic()
    out, n = s1.allreduce("g", np.full(2, 5.0, np.float32), timeout=30)
    dt = time.monotonic() - t0
    assert n == 1 and np.allclose(out, 5.0)
    assert dt < 10, f"release took {dt:.1f}s — timed out, not released"
    s1.close()
    srv.stop()


def test_dead_rank_releases_barrier_without_timeout():
    srv = _server(barrier_timeout=60.0)
    s1 = _session(srv, rank=0)
    s2 = _session(srv, rank=1)
    s1.ensure_joined(wait_for_expected=False)
    s2.ensure_joined(wait_for_expected=False)
    s2._hb.stop()
    t0 = time.monotonic()
    s1.barrier(timeout=30.0)  # must release well under barrier_timeout
    assert time.monotonic() - t0 < 10
    s1.close()
    srv.stop()


def test_dead_rank_releases_epoch_rendezvous():
    srv = _server()
    s1 = _session(srv, rank=0)
    s2 = _session(srv, rank=1)
    s1.ensure_joined(wait_for_expected=False)
    s2.ensure_joined(wait_for_expected=False)
    res = {}
    ts = [threading.Thread(
        target=lambda s=s, n=n: res.update(
            {n: s.allreduce("g", np.ones(1, np.float32), timeout=30)}))
        for n, s in (("a", s1), ("b", s2))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert res["a"][1] == 2  # both contributed → fleet is "started"
    s2._hb.stop()  # dies while s1 waits at the boundary
    info = s1.epoch_end(0, timeout=30)
    assert info.num_parts == 1 and info.part_index == 0
    s1.close()
    srv.stop()


def test_reduce_retry_idempotent_under_dropped_reply():
    """A lost reduce ack retries the SAME (cid, round): the server must
    serve the cached released round, not fold the contribution twice."""
    srv = _server()
    s1 = _session(srv, rank=0)
    s2 = _session(srv, rank=1)
    s1.ensure_joined(wait_for_expected=False)
    s2.ensure_joined(wait_for_expected=False)
    chaos_rpc.configure([chaos_rpc.Rule("reduce", "drop_reply", {1})])
    try:
        res = {}
        ts = [threading.Thread(
            target=lambda s=s, name=name, v=v: res.update(
                {name: s.allreduce("g", np.full(3, v, np.float32),
                                   timeout=30)}))
            for name, s, v in (("a", s1, 1.0), ("b", s2, 2.0))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
    finally:
        chaos_rpc.reset()
    assert np.allclose(res["a"][0], 3.0) and np.allclose(res["b"][0], 3.0)
    assert res["a"][1] == 2 and res["b"][1] == 2
    s1.close()
    s2.close()
    srv.stop()


# ---------------------------------------------------------------------------
# structured barrier timeout (satellite)
# ---------------------------------------------------------------------------

def test_barrier_timeout_names_missing_ranks():
    srv = _server(barrier_timeout=1.0)
    s1 = _session(srv, rank=0)
    s2 = _session(srv, rank=1)
    s1.ensure_joined(wait_for_expected=False)
    s2.ensure_joined(wait_for_expected=False)
    # s2 is alive and heartbeating but never arrives at the barrier
    with pytest.raises(TimeoutError) as ei:
        s1.barrier(timeout=20.0)
    msg = str(ei.value)
    assert "rank 1" in msg and "last heartbeat" in msg, msg
    assert "1/2 arrived" in msg, msg
    s1.close()
    s2.close()
    srv.stop()


def test_barrier_timeout_detail_without_membership_reports_counts():
    """Legacy fleets (no heartbeats) can't name ranks — the structured
    error still reports arrived/expected instead of a generic shrug."""
    from mxnet_tpu.kvstore.ps_client import PSClient

    srv = _server(num_workers=2, barrier_timeout=0.5)
    cli = PSClient("127.0.0.1", srv.port, timeout=5, retries=1)
    with pytest.raises(TimeoutError) as ei:
        cli.barrier(timeout=10.0)
    assert "1/2 arrived" in str(ei.value), str(ei.value)
    srv.stop()


# ---------------------------------------------------------------------------
# PS durability: snapshots + WAL (satellite / acceptance)
# ---------------------------------------------------------------------------

def test_ps_warm_restart_restores_weights_seq_and_optimizer(tmp_path):
    import mxnet_tpu as mx
    from mxnet_tpu.kvstore.ps_client import PSClient
    from mxnet_tpu.kvstore.ps_server import (OP_PUSH_SEQ, PSServer,
                                             _pack_array)

    srv = PSServer(host="127.0.0.1", port=0, snapshot_dir=str(tmp_path),
                   snapshot_period=0)
    srv.start()
    cli = PSClient("127.0.0.1", srv.port, timeout=5, retries=3,
                   retry_interval=0.05)
    cli.init("w", np.ones(4, np.float32))
    cli.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0))
    cli.push("w", np.full(4, 2.0, np.float32))  # seq 1: w -= 0.1*2 → 0.8
    cid = cli._client_id
    srv.snapshot_now()
    cli.push("w", np.full(4, 2.0, np.float32))  # seq 2: WAL-only → 0.6
    srv.stop()

    srv2 = PSServer(host="127.0.0.1", port=0, snapshot_dir=str(tmp_path),
                    snapshot_period=0)
    srv2.start()
    cli2 = PSClient("127.0.0.1", srv2.port, timeout=5, retries=3,
                    retry_interval=0.05)
    np.testing.assert_allclose(cli2.pull("w"), 0.6, rtol=1e-6)
    # the lost-ack replay: same (cid, seq) must be deduped after restart
    payload = struct.pack("<QQ", cid, 2) + _pack_array(
        np.full(4, 2.0, np.float32))
    _, _, reply = cli2._rpc(OP_PUSH_SEQ, "w", payload)
    assert bytes(reply[:1]) == b"\x00"
    np.testing.assert_allclose(cli2.pull("w"), 0.6, rtol=1e-6)
    # and the restored server optimizer keeps applying updates
    cli2.push("w", np.full(4, 1.0, np.float32))
    np.testing.assert_allclose(cli2.pull("w"), 0.5, rtol=1e-6)
    srv2.stop()


def test_ps_wal_torn_tail_record_is_ignored_and_truncated(tmp_path):
    from mxnet_tpu.kvstore.elastic import PushWAL

    wal = PushWAL(str(tmp_path))
    wal.rotate(0)
    wal.append(0, 7, 1, "w", b"payload-1")
    wal.append(0, 7, 2, "w", b"payload-2")
    wal.close()
    # SIGKILL mid-append: truncate the last record's tail
    path = os.path.join(str(tmp_path), "wal-00000000.bin")
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 3)
    seen = []
    wal2 = PushWAL(str(tmp_path))
    n = wal2.replay(lambda kind, cid, seq, key, payload: seen.append(seq))
    assert n == 1 and seen == [1]
    # the warm-restarted server reopens the SAME file for appending —
    # replay must have truncated the torn bytes, or an acked record
    # written behind them would be unreachable at the NEXT restart
    wal2.rotate(0)
    wal2.append(0, 7, 3, "w", b"payload-3")
    wal2.close()
    seen2 = []
    wal3 = PushWAL(str(tmp_path))
    wal3.replay(lambda kind, cid, seq, key, payload: seen2.append(seq))
    assert seen2 == [1, 3], seen2
    wal3.close()


def test_ps_wal_replays_births_before_pushes(tmp_path):
    """The live handlers append a key's birth (kind 2) and its pushes on
    different locks, so an acked push can land in the log AHEAD of the
    birth record — replay must apply births first or that acked push is
    silently dropped."""
    from mxnet_tpu.kvstore.elastic import PushWAL
    from mxnet_tpu.kvstore.ps_server import PSServer, _pack_array

    wal = PushWAL(str(tmp_path))
    wal.rotate(0)
    wal.append(0, 7, 1, "w", _pack_array(np.ones(3, np.float32)))
    wal.append(2, 0, 0, "w", _pack_array(np.full(3, 5.0, np.float32)))
    wal.close()
    srv = PSServer(host="127.0.0.1", port=0, snapshot_dir=str(tmp_path),
                   snapshot_period=0)
    np.testing.assert_allclose(srv._weights["w"], 6.0)
    srv.stop()


def test_zombie_barrier_arrival_rejected_not_counted():
    """A declared-dead-but-running worker's barrier arrival must not count
    toward the LIVE quorum (it would release a round a live member never
    reached) — it gets the structured stale rejection, and the live member
    still releases alone."""
    srv = _server(barrier_timeout=30.0)
    s1 = _session(srv, rank=0)
    s2 = _session(srv, rank=1)
    s1.ensure_joined(wait_for_expected=False)
    s2.ensure_joined(wait_for_expected=False)
    s2._hb.stop()
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        with srv._elastic.cv:
            if srv._elastic.members[s2.cid].state == "dead":
                break
        time.sleep(0.05)
    with pytest.raises(el.StaleMemberError):
        s2.barrier(timeout=10.0)
    t0 = time.monotonic()
    s1.barrier(timeout=20.0)  # quorum is {s1} alone — must release
    assert time.monotonic() - t0 < 10
    s1.close()
    srv.stop()


def test_elastic_fleet_survives_ps_warm_restart(tmp_path):
    """With durable snapshots on, MEMBERSHIP rides the snapshot: after a
    PS bounce the restored members just keep heartbeating and the next
    reduce retries idempotently against the fresh tables — the fleet must
    NOT collapse into stale rejections."""
    from mxnet_tpu.kvstore.ps_server import PSServer

    srv = _server(snapshot_dir=str(tmp_path), snapshot_period=0)
    port = srv.port
    s1 = _session(srv, rank=0)
    s2 = _session(srv, rank=1)
    s1.ensure_joined(wait_for_expected=False)
    s2.ensure_joined(wait_for_expected=False)
    res = {}
    ts = [threading.Thread(
        target=lambda s=s, n=n: res.update(
            {n: s.allreduce("g", np.ones(2, np.float32), timeout=30)}))
        for n, s in (("a", s1), ("b", s2))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert res["a"][1] == 2
    srv.snapshot_now()
    srv.stop()
    srv2 = None
    deadline = time.monotonic() + 10
    while srv2 is None:
        try:
            srv2 = PSServer(host="127.0.0.1", port=port, hb_interval=_HB,
                            miss_k=_MISS, snapshot_dir=str(tmp_path),
                            snapshot_period=0)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.1)
    srv2.start()
    res2 = {}
    ts = [threading.Thread(
        target=lambda s=s, n=n: res2.update(
            {n: s.allreduce("g2", np.full(2, 2.0, np.float32),
                            timeout=30)}))
        for n, s in (("a", s1), ("b", s2))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert res2["a"][1] == 2 and np.allclose(res2["a"][0], 4.0), res2
    s1.close()
    s2.close()
    srv2.stop()


def test_epoch_rendezvous_resyncs_a_behind_server():
    """Workers resuming from shared checkpoints at epoch N against a
    fresh/unsnapshotted server (epoch 0) must not wedge: the fleet's
    epoch is authoritative and the server jumps forward."""
    srv = _server()
    s1 = _session(srv, rank=0)
    s2 = _session(srv, rank=1)
    s1.ensure_joined(wait_for_expected=False)
    s2.ensure_joined(wait_for_expected=False)
    got = {}
    ts = [threading.Thread(
        target=lambda s=s, n=n: got.update({n: s.epoch_end(5, timeout=20)}))
        for n, s in (("a", s1), ("b", s2))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=40)
    assert got["a"].epoch == 6 and got["b"].epoch == 6, got
    s1.close()
    s2.close()
    srv.stop()


def test_dead_member_heartbeat_does_not_refresh_liveness():
    """A zombie's continuing heartbeats must not reset last_hb once it is
    declared dead — that would defeat the prune GC forever."""
    st = ElasticState(hb_interval=0.05, miss_k=2)
    st.join(1, 0)
    with st.cv:
        st.members[1].state = "dead"
        stamp = st.members[1].last_hb
    time.sleep(0.05)
    status, _gen, _count = st.heartbeat(1)
    assert status == el.ST_STALE
    with st.cv:
        assert st.members[1].last_hb == stamp
    st.close()


def test_barrier_waits_for_live_members_not_arrival_count():
    """A member that arrives at the barrier and THEN dies must not stand
    in for a live member that never arrived — release requires the live
    cid set to be a subset of the arrived cids, not a raw count."""
    srv = _server(barrier_timeout=60.0)
    ss = [_session(srv, rank=r) for r in range(3)]
    for s in ss:
        s.ensure_joined(wait_for_expected=False)
    done = {}
    t1 = threading.Thread(target=lambda: done.update(
        a=ss[0].barrier(timeout=40)))
    t1.start()
    ss[0]._hb.stop()  # arrives, then dies

    def _pump_live(until):
        # keep s2/s3 deterministically alive from the test thread: on a
        # loaded box their Heartbeater threads can starve past the window
        # and a legitimate quorum shrink would mask the regression
        while time.monotonic() < until:
            srv._elastic.heartbeat(ss[1].cid)
            srv._elastic.heartbeat(ss[2].cid)
            with srv._elastic.cv:
                dead = srv._elastic.members[ss[0].cid].state == "dead"
            if dead:
                return True
            time.sleep(0.05)
        return False

    assert _pump_live(time.monotonic() + 15), "victim never declared dead"
    t2 = threading.Thread(target=lambda: done.update(
        b=ss[1].barrier(timeout=40)))
    t2.start()
    until = time.monotonic() + 1.5
    while time.monotonic() < until:
        srv._elastic.heartbeat(ss[1].cid)
        srv._elastic.heartbeat(ss[2].cid)
        time.sleep(0.05)
    # live quorum is {s2, s3}: s1's (dead) arrival + s2 must NOT release
    assert "b" not in done, "barrier released while a live member missing"
    ss[2].barrier(timeout=40)
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert not t1.is_alive() and not t2.is_alive()
    for s in ss[1:]:
        s.close()
    srv.stop()


def test_set_partition_trims_to_equal_batch_counts():
    """Recut shards must be EQUAL-sized (drop-last over the remainder):
    elastic sync is lockstep, and unequal per-rank batch counts would
    wedge the longer ranks in reduce rounds nobody else joins."""
    from mxnet_tpu.io import NDArrayIter

    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    sizes = []
    for part in range(4):
        it = NDArrayIter({"data": x}, batch_size=1)
        it.set_partition(part, 4)
        sizes.append(it.num_data)
    assert sizes == [2, 2, 2, 2], sizes
    # a PRE-SHARDED iterator (classic part_index= construction) must be
    # trimmed too: the unchanged-(part, nparts) call may not short-circuit
    # around the equal-size cut
    sizes = []
    for part in range(3):
        it = NDArrayIter({"data": x}, batch_size=1, part_index=part,
                         num_parts=3)
        it.set_partition(part, 3)
        sizes.append(it.num_data)
    assert sizes == [3, 3, 3], sizes


def test_epoch_jump_clears_collective_tables():
    """Mixed-epoch arrivals against a behind server: the forward jump is a
    boundary resync and must clear the released-round cache — a lower-
    epoch waiter released by the jump restarts its round numbering and
    must not be answered with pre-jump cached sums."""
    # wide liveness window: this unit never heartbeats and exercises the
    # jump semantics, not death declaration
    st = ElasticState(hb_interval=1.0, miss_k=60)
    st.join(1, 0)
    st.join(2, 1)
    done = {}
    ts = [threading.Thread(
        target=lambda cid=cid, v=v: done.update({cid: st.reduce(
            cid, "g", 0, np.full(2, v, np.float32), timeout=10)}))
        for cid, v in ((1, 1.0), (2, 2.0))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert np.allclose(done[1][3], 3.0)
    got = {}
    t1 = threading.Thread(target=lambda: got.update(
        a=st.epoch_end(1, 1, timeout=15)))
    t1.start()
    time.sleep(0.2)
    # cid 2 jumps the epoch to 5; cid 1's lower-epoch wait exits released
    # (cid 2's own boundary-5 wait can't complete — that's the documented
    # mixed-epoch desync, surfaced as a timeout, not silent corruption)
    got["b"] = st.epoch_end(2, 5, timeout=2)
    t1.join(timeout=30)
    assert not t1.is_alive()
    with st.cv:
        assert not st._completed and not st._rounds
    # a post-jump round 0 must gather fresh, not serve the pre-jump cache
    ts = [threading.Thread(
        target=lambda cid=cid, v=v: done.update({cid: st.reduce(
            cid, "g", 0, np.full(2, v, np.float32), timeout=10)}))
        for cid, v in ((1, 5.0), (2, 6.0))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert np.allclose(done[1][3], 11.0), done[1]
    st.close()


def test_fleet_takeover_clears_cached_reduce_rounds():
    """A joiner activated by fleet takeover restarts round numbering at 0;
    the dead fleet's released-round cache must not answer its round 0 with
    a stale gradient sum."""
    st = ElasticState(hb_interval=0.05, miss_k=3)
    st.join(1, 0)
    st.join(2, 1)
    done = {}
    ts = [threading.Thread(
        target=lambda cid=cid, v=v: done.update({cid: st.reduce(
            cid, "g", 0, np.full(2, v, np.float32), timeout=10)}))
        for cid, v in ((1, 10.0), (2, 20.0))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert np.allclose(done[1][3], 30.0)  # old fleet's round 0 = 30
    st.join(3, 2)  # started fleet → quarantined
    st.leave(1)
    st.leave(2)  # last active leaves → takeover activates cid 3
    with st.cv:
        assert st.members[3].state == "active"
    status, _gen, n, out = st.reduce(3, "g", 0, np.full(2, 5.0, np.float32),
                                     timeout=10)
    assert status == el.ST_OK and n == 1 and np.allclose(out, 5.0), \
        (status, n, out)
    st.close()


# ---------------------------------------------------------------------------
# half-open detection: keepalive + idle ping (satellite)
# ---------------------------------------------------------------------------

def test_idle_ping_recovers_from_restarted_server():
    """A server restarted behind an idle connection is detected by the
    ping-before-reuse probe at the NEXT rpc — the stale socket is dropped
    and the rpc reconnect-retries instead of writing into a dead pipe."""
    from mxnet_tpu.kvstore.ps_client import PSClient
    from mxnet_tpu.kvstore.ps_server import PSServer

    srv = PSServer(host="127.0.0.1", port=0)
    srv.start()
    port = srv.port
    cli = PSClient("127.0.0.1", port, timeout=5, retries=4,
                   retry_interval=0.1, idle_ping=0.05)
    cli.init("w", np.zeros(2, np.float32))
    srv.stop()
    srv2 = None
    deadline = time.monotonic() + 10
    while srv2 is None:  # the old listener may take a beat to release
        try:
            srv2 = PSServer(host="127.0.0.1", port=port)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.1)
    srv2.start()
    time.sleep(0.1)  # connection is now idle past the ping threshold
    t0 = time.monotonic()
    cli.init("w", np.zeros(2, np.float32))  # must reconnect, not hang
    assert time.monotonic() - t0 < 5
    np.testing.assert_array_equal(cli.pull("w"), np.zeros(2, np.float32))
    srv2.stop()


def test_sockets_carry_keepalive():
    from mxnet_tpu.kvstore.ps_client import PSClient
    from mxnet_tpu.kvstore.ps_server import PSServer

    srv = PSServer(host="127.0.0.1", port=0)
    srv.start()
    cli = PSClient("127.0.0.1", srv.port, timeout=5)
    assert cli._sock.getsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE) == 1
    srv.stop()


# ---------------------------------------------------------------------------
# iterator shard recut (io/)
# ---------------------------------------------------------------------------

def test_ndarray_iter_set_partition_recuts_at_boundary():
    from mxnet_tpu.io import NDArrayIter

    x = np.arange(12, dtype=np.float32).reshape(12, 1)
    it = NDArrayIter({"data": x}, batch_size=2, part_index=1, num_parts=3)
    assert it.num_data == 4
    got = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    np.testing.assert_array_equal(got, [4, 5, 6, 7])
    # survivor absorbs a dead rank's shard: recut 3 → 2 parts
    it.set_partition(0, 2)
    it.reset()
    assert it.num_data == 6
    got = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    np.testing.assert_array_equal(got, [0, 1, 2, 3, 4, 5])
    # positioning contract still holds after a recut
    state = it.get_checkpoint_state()
    assert len(state["order"]) == 6


def test_prefetching_iter_delegates_set_partition():
    from mxnet_tpu.io import NDArrayIter, PrefetchingIter

    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    it = PrefetchingIter(NDArrayIter({"data": x}, batch_size=2))
    it.set_partition(0, 2)
    it.reset()
    got = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    np.testing.assert_array_equal(got, [0, 1, 2, 3])
    it.close()


# ---------------------------------------------------------------------------
# flagship chaos runs (slow, subprocess)
# ---------------------------------------------------------------------------

def _worker_env(rank, n, ps_port, hb="0.2", miss="3"):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "MXNET_ELASTIC": "1",
        "MXNET_ELASTIC_HEARTBEAT_S": hb,
        "MXNET_ELASTIC_MISS_K": miss,
        "MXNET_PS_ADDR": "127.0.0.1",
        "MXNET_PS_PORT": str(ps_port),
        "DMLC_NUM_WORKER": str(n),
        "DMLC_WORKER_ID": str(rank),
        "DMLC_ROLE": "worker",
    })
    return env


class _Tail:
    """Line collector with marker waits over a worker's stdout."""

    def __init__(self, proc):
        self.proc = proc
        self.lines = []
        self._cv = threading.Condition()
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        for line in self.proc.stdout:
            with self._cv:
                self.lines.append(line.rstrip("\n"))
                self._cv.notify_all()

    def wait_for(self, pred, timeout):
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                for ln in self.lines:
                    if pred(ln):
                        return ln
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self.proc.poll() is not None:
                    for ln in self.lines:  # final sweep after exit
                        if pred(ln):
                            return ln
                    return None
                self._cv.wait(timeout=min(remaining, 0.5))

    def text(self):
        with self._cv:
            return "\n".join(self.lines)


def _spawn_ps(port, snapshot_dir=None, env=None):
    cmd = [sys.executable, "-m", "mxnet_tpu.kvstore.ps_server",
           "--port", str(port)]
    if snapshot_dir:
        cmd += ["--snapshot-dir", str(snapshot_dir),
                "--snapshot-period", "0.5"]
    e = dict(os.environ)
    e.update({"JAX_PLATFORMS": "cpu",
              "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    e.update(env or {})
    proc = subprocess.Popen(cmd, env=e, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    tail = _Tail(proc)
    assert tail.wait_for(lambda l: "listening" in l, 90), tail.text()
    return proc, tail


def _spawn_worker(rank, n, ps_port, ckpt, epochs=4, step_delay=0.0):
    cmd = [sys.executable, WORKER, "--ckpt-dir", str(ckpt),
           "--epochs", str(epochs)]
    if step_delay:
        cmd += ["--step-delay", str(step_delay)]
    proc = subprocess.Popen(
        cmd, env=_worker_env(rank, n, ps_port), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    return proc, _Tail(proc)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _final_loss(tail):
    ln = tail.wait_for(lambda l: l.startswith("FINAL_LOSS"), 1)
    return float(ln.split()[1]) if ln else None


@pytest.mark.slow
def test_flagship_worker_death_rebalance_and_rejoin(tmp_path):
    """SIGKILL 1 of 3 elastic dist_sync workers mid-epoch: survivors
    finish the epoch (reduce released over the live generation, no barrier
    timeout), recut shards 3→2 at the boundary, the restarted worker
    rejoins quarantined → activated at the next boundary (3 parts again)
    from the shared checkpoint, and the fleet's final loss matches an
    uninjected run within documented tolerance."""
    # step_delay stretches each epoch to a few seconds so the restarted
    # worker's interpreter+jax startup (~5-10s) lands while the fleet is
    # still mid-training — otherwise the survivors would finish before
    # the rejoin could happen at all
    epochs, delay = 6, 0.4
    port = _free_port()
    ps, _ps_tail = _spawn_ps(port)
    procs = {}
    try:
        for r in range(3):
            procs[r] = _spawn_worker(r, 3, port, tmp_path / "ckpt",
                                     epochs=epochs, step_delay=delay)
        victim, vtail = procs[2]
        # mid-epoch-0 kill: each epoch-0 shard is 4 steps; die at step 2
        assert vtail.wait_for(
            lambda l: l.startswith("CHAOS_STEP 2"), 120), vtail.text()
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=30)
        assert victim.returncode == -signal.SIGKILL
        # survivors must reach epoch 1 with the shard recut to 2 parts —
        # released by the death declaration, NOT a barrier timeout
        w0, t0 = procs[0]
        assert t0.wait_for(
            lambda l: l.startswith("EPOCH_START 1 parts=2"), 120), t0.text()
        # restart the victim: it joins quarantined, is activated at the
        # next boundary at the committed generation, and restores from the
        # shared checkpoint; once back, the shard cut is 3 ways again
        procs[2] = _spawn_worker(2, 3, port, tmp_path / "ckpt",
                                 epochs=epochs, step_delay=delay)
        _, rtail = procs[2]
        assert rtail.wait_for(
            lambda l: l.startswith("EPOCH_START") and "parts=3" in l,
            240), rtail.text()
        assert t0.wait_for(
            lambda l: l.startswith("EPOCH_START") and "parts=3" in l,
            240), t0.text()
        rcs = {}
        for r, (proc, tail) in procs.items():
            proc.wait(timeout=300)
            rcs[r] = proc.returncode
        assert all(rc == 0 for rc in rcs.values()), \
            {r: procs[r][1].text()[-3000:] for r in procs}
        # rejoiner rebalanced back to 3 parts and finished in lockstep:
        # identical final loss on every rank (identical params)
        losses = {r: _final_loss(procs[r][1]) for r in procs}
        assert all(v is not None for v in losses.values()), losses
        assert len({round(v, 6) for v in losses.values()}) == 1, losses
    finally:
        for proc, _ in procs.values():
            if proc.poll() is None:
                proc.kill()
        ps.terminate()
        ps.wait(timeout=10)

    # uninjected reference run → documented tolerance (ROBUSTNESS.md):
    # the injected fleet dropped the victim's tail batches of epoch 0 and
    # averaged 2 shards for one epoch — same problem, same lr schedule,
    # so the final loss must land in the same regime
    port2 = _free_port()
    ps2, _ = _spawn_ps(port2)
    clean = {}
    try:
        for r in range(3):
            clean[r] = _spawn_worker(r, 3, port2, tmp_path / "ckpt_clean",
                                     epochs=epochs, step_delay=delay)
        for r, (proc, tail) in clean.items():
            proc.wait(timeout=300)
            assert proc.returncode == 0, tail.text()[-3000:]
        clean_loss = _final_loss(clean[0][1])
    finally:
        for proc, _ in clean.values():
            if proc.poll() is None:
                proc.kill()
        ps2.terminate()
        ps2.wait(timeout=10)
    injected_loss = losses[0]
    assert clean_loss is not None and injected_loss is not None
    assert abs(injected_loss - clean_loss) <= 0.25 * max(clean_loss, 1.0), \
        (injected_loss, clean_loss)


@pytest.mark.slow
def test_flagship_ps_sigkill_mid_push_warm_restart_exactly_once(tmp_path):
    """SIGKILL the PS server with an update applied but unacked
    (ps:post_apply), warm-restart it from the durable snapshot + WAL, and
    prove zero lost / zero double-applied across the whole lossy session:
    the final weight equals the exact sum of every pushed gradient."""
    from mxnet_tpu.kvstore.ps_client import PSClient

    port = _free_port()
    snap = tmp_path / "ps_state"
    ps, tail = _spawn_ps(port, snapshot_dir=snap,
                         env={"MXNET_CHAOS_KILL": "ps:post_apply@3"})
    restarted = threading.Event()

    def _supervisor():
        ps.wait()
        if ps.returncode == -signal.SIGKILL:
            ps2, _ = _spawn_ps(port, snapshot_dir=snap)
            restarted.ps2 = ps2
            restarted.set()

    sup = threading.Thread(target=_supervisor, daemon=True)
    sup.start()
    cli = PSClient("127.0.0.1", port, timeout=10, retries=14,
                   retry_interval=0.5, retry_max_interval=3.0)
    cli.init("w", np.zeros(3, np.float32))
    total = np.zeros(3, np.float32)
    for i in range(1, 7):
        g = np.full(3, float(i), np.float32)
        cli.push("w", g)  # push 3 kills the server post-apply, pre-ack
        total += g
    sup.join(timeout=120)
    assert restarted.is_set(), "server was never SIGKILL'd+restarted"
    np.testing.assert_array_equal(cli.pull("w"), total)
    restarted.ps2.terminate()
    restarted.ps2.wait(timeout=10)
