"""gluon.data tests (reference tests/python/unittest/test_gluon_data.py analog)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import data as gdata
from mxnet_tpu.gluon.data.vision import SyntheticImageDataset, transforms


def test_array_dataset_and_loader():
    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    y = np.arange(10, dtype=np.int32)
    ds = gdata.ArrayDataset(x, y)
    assert len(ds) == 10
    loader = gdata.DataLoader(ds, batch_size=3, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (3, 4)
    assert batches[-1][0].shape == (1, 4)
    np.testing.assert_allclose(batches[0][0].asnumpy(), x[:3])


def test_loader_discard_rollover():
    ds = gdata.ArrayDataset(np.arange(10, dtype=np.float32))
    assert len(list(gdata.DataLoader(ds, batch_size=3, last_batch="discard"))) == 3
    loader = gdata.DataLoader(ds, batch_size=3, last_batch="rollover")
    assert len(list(loader)) == 3          # 1 sample rolls over
    assert len(list(loader)) == 3          # 1+10 = 11 -> 3 batches, 2 roll


def test_loader_shuffle_covers_all():
    ds = gdata.ArrayDataset(np.arange(20, dtype=np.float32))
    seen = np.concatenate([b.asnumpy() for b in
                           gdata.DataLoader(ds, batch_size=4, shuffle=True)])
    assert sorted(seen.tolist()) == list(range(20))


def test_loader_threaded_workers():
    ds = SyntheticImageDataset(length=17, shape=(1, 8, 8), num_classes=4)
    loader = gdata.DataLoader(ds, batch_size=4, num_workers=2)
    batches = list(loader)
    assert sum(b[0].shape[0] for b in batches) == 17
    # determinism of the synthetic data itself
    a0 = ds[3][0].asnumpy()
    a1 = ds[3][0].asnumpy()
    np.testing.assert_array_equal(a0, a1)


def test_dataset_shard_and_take():
    ds = gdata.ArrayDataset(np.arange(10, dtype=np.float32))
    shards = [ds.shard(3, i) for i in range(3)]
    assert [len(s) for s in shards] == [4, 3, 3]
    all_vals = sorted(float(s[i]) for s in shards for i in range(len(s)))
    assert all_vals == list(range(10))
    assert len(ds.take(4)) == 4


def test_transform_first_and_sampler():
    x = np.ones((6, 2, 2, 1), np.uint8) * 255
    y = np.arange(6, dtype=np.int32)
    ds = gdata.ArrayDataset(x, y).transform_first(transforms.ToTensor())
    img, label = ds[2]
    assert img.shape == (1, 2, 2)
    np.testing.assert_allclose(img.asnumpy(), 1.0)
    assert label == 2


def test_transforms_pipeline():
    t = transforms.Compose([
        transforms.Resize(16),
        transforms.CenterCrop(12),
        transforms.RandomFlipLeftRight(),
        transforms.ToTensor(),
        transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.25, 0.25, 0.25)),
    ])
    img = nd.array(np.random.randint(0, 255, (20, 24, 3)).astype(np.uint8))
    out = t(img)
    assert out.shape == (3, 12, 12)
    assert out.dtype == np.float32


def test_random_resized_crop_and_jitter():
    img = nd.array(np.random.randint(0, 255, (32, 32, 3)).astype(np.uint8))
    out = transforms.RandomResizedCrop(16)(img)
    assert out.shape == (16, 16, 3)
    out = transforms.RandomColorJitter(0.4, 0.4, 0.4)(img)
    assert out.shape == (32, 32, 3)


def test_batch_sampler_api():
    s = gdata.BatchSampler(gdata.SequentialSampler(7), 2, "discard")
    assert len(s) == 3
    assert list(s) == [[0, 1], [2, 3], [4, 5]]


def test_filter_dataset():
    ds = gdata.ArrayDataset(np.arange(10, dtype=np.float32))
    even = ds.filter(lambda x: int(x) % 2 == 0)
    assert len(even) == 5
