"""Serving subsystem suite (``pytest -m serve`` / ``make serve``).

Covers the docs/SERVING.md contracts:

1. engine — shape bucketing with a *provable* compiled-program bound
   (``profiler.count_dispatches`` + the TraceLinter ``serve-retrace-churn``
   rule), batched-vs-single bitwise equality, oversize chunking, warmup;
2. batcher — linger coalescing, deadline-expired requests shed (never
   executed), priority lanes immune to head-of-line blocking, watermark
   load shedding;
3. hot reload — concurrent traffic sees old-or-new parameters, never a
   mix; aval drift is rejected;
4. endpoint — health/readiness probes, draining shutdown, chaos
   drop/dup on the serve socket degrades to a retry (not an error), and
   the flagship: train a model-zoo CNN 2 batches → checkpoint →
   ``serve.load`` → concurrent mixed-shape clients get outputs bitwise
   identical to direct ``Module.predict``, with program count ≤ buckets
   and a chrome trace carrying complete ``serve.*`` phase spans.
"""
import json
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, obs, profiler, serve
from mxnet_tpu import symbol as sym
from mxnet_tpu.analysis.trace import TraceLinter
from mxnet_tpu.chaos import rpc as chaos_rpc
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import Module
from mxnet_tpu.serve import (DeadlineExceeded, Draining, DynamicBatcher,
                             InferenceEngine, RequestRejected, ServeClient,
                             ServeError, ServeServer, default_buckets)

pytestmark = pytest.mark.serve


@pytest.fixture(autouse=True)
def _clean():
    chaos_rpc.reset()
    yield
    chaos_rpc.reset()
    obs.disable()
    obs.reset()


def _linear_engine(scale=1.0, dim=4, max_batch=8, **kw):
    """y = x @ (scale * I): outputs are exactly scale * x (bitwise — each
    row of the matmul has a single nonzero product), which makes
    old-vs-new parameter provenance decidable per output."""
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=dim, no_bias=True, name="fc")
    arg = {"fc_weight": np.eye(dim, dtype=np.float32) * scale}
    return net, arg, InferenceEngine(net, arg, max_batch_size=max_batch,
                                     lint="off", **kw)


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    arg = {"fc1_weight": rng.randn(16, 6).astype(np.float32) * 0.3,
           "fc1_bias": rng.randn(16).astype(np.float32) * 0.1,
           "fc2_weight": rng.randn(4, 16).astype(np.float32) * 0.3,
           "fc2_bias": np.zeros(4, np.float32)}
    return net, arg


class _FakeEngine:
    """Duck-typed engine for scheduler tests: deterministic, recordable,
    optionally slow — so deadline/priority behavior is tested without
    racing real XLA execution times."""

    def __init__(self, delay=0.0, max_batch_size=8):
        self.delay = delay
        self.max_batch_size = max_batch_size
        self.buckets = default_buckets(max_batch_size)
        self.calls = []  # list of (rows, t_start)

    def infer(self, inputs, n_valid=None):
        x = inputs[0]
        self.calls.append((int(x.shape[0]), time.monotonic()))
        if self.delay:
            time.sleep(self.delay)
        return [np.asarray(x) * 2.0], 0


# ---------------------------------------------------------------------------
# 1. engine: bucketing, program bound, bitwise equality
# ---------------------------------------------------------------------------

def test_default_buckets():
    assert default_buckets(1) == [1]
    assert default_buckets(8) == [1, 2, 4, 8]
    assert default_buckets(24) == [1, 2, 4, 8, 16, 24]
    with pytest.raises(ValueError):
        default_buckets(0)


def test_bucketing_program_count_bound():
    """Ragged request shapes never grow the program count past the bucket
    bound — asserted three independent ways: the engine's own accounting,
    profiler.count_dispatches (one compiled execution per infer, no hidden
    retrace dispatches), and the TraceLinter churn rule."""
    net, arg = _mlp()
    engine = InferenceEngine(net, arg, max_batch_size=8, lint="off")
    rng = np.random.RandomState(1)
    ragged = [3, 1, 5, 2, 8, 7, 4, 6, 3, 1, 5]
    for n in ragged:
        engine.predict(rng.rand(n, 6).astype(np.float32))
    assert engine.num_programs <= len(engine.buckets) == 4
    assert engine.exec_count == len(ragged)
    # steady state: a seen shape costs exactly ONE compiled execution and
    # zero compilations
    before = engine.num_programs
    with profiler.count_dispatches() as c:
        engine.predict(rng.rand(3, 6).astype(np.float32))
    assert engine.num_programs == before
    assert c.total_compiled == 1
    # the linter-backed proof: an empty finding list
    assert TraceLinter().check_serve_engine(engine) == []
    # negative control: a duplicated compile_log signature must be flagged
    engine.compile_log.append(engine.compile_log[0])
    bad = TraceLinter().check_serve_engine(engine)
    assert bad and bad[0].rule_id == "serve-retrace-churn"


def test_batched_vs_single_request_bitwise():
    """One 6-row batch vs six 1-row requests routed through the SAME
    bucket program: row outputs are bitwise identical — rows are
    independent in eval mode and padding never contaminates valid rows.
    (The same-program condition is the honest contract: XLA only promises
    identical ulps across runs of one executable, which is why the
    batcher coalesces concurrent singles into one bucket instead of
    running per-request programs.)"""
    net, arg = _mlp()
    engine = InferenceEngine(net, arg, buckets=(8,), lint="off")
    rng = np.random.RandomState(2)
    x = rng.rand(6, 6).astype(np.float32)
    batched = engine.predict(x)
    for i in range(6):
        single = engine.predict(x[i:i + 1])
        assert np.array_equal(single[0], batched[i]), f"row {i} differs"
    assert engine.num_programs == 1


def test_engine_oversize_request_chunks():
    net, arg = _mlp()
    engine = InferenceEngine(net, arg, max_batch_size=4, lint="off")
    rng = np.random.RandomState(3)
    x = rng.rand(11, 6).astype(np.float32)  # > top bucket: 4+4+3 chunks
    out = engine.predict(x)
    assert out.shape == (11, 4)
    ref = engine.predict(x[:4])
    assert np.array_equal(out[:4], ref)
    assert engine.num_programs <= len(engine.buckets)


def test_engine_warmup_precompiles_every_bucket():
    net, arg = _mlp()
    engine = InferenceEngine(net, arg, max_batch_size=8, lint="off")
    compiled = engine.warmup((6,))
    assert compiled == len(engine.buckets) == engine.num_programs
    with profiler.count_dispatches() as c:
        engine.predict(np.zeros((5, 6), np.float32))
    assert c.total_compiled == 1 and engine.num_programs == compiled


def test_engine_lint_preflight_runs_at_load():
    net, arg = _mlp()
    engine = InferenceEngine(net, arg, max_batch_size=2, lint="warn")
    assert engine.lint_report is not None  # analyzer ran before serving


def test_engine_rejects_missing_aux():
    data = sym.Variable("data")
    net = sym.BatchNorm(sym.FullyConnected(data, num_hidden=4, name="fc"),
                        name="bn")
    rng = np.random.RandomState(0)
    arg = {"fc_weight": rng.randn(4, 6).astype(np.float32),
           "fc_bias": np.zeros(4, np.float32),
           "bn_gamma": np.ones(4, np.float32),
           "bn_beta": np.zeros(4, np.float32)}
    with pytest.raises(ServeError, match="aux"):
        InferenceEngine(net, arg, lint="off")


# ---------------------------------------------------------------------------
# 2. batcher: linger, deadlines, priorities, shedding
# ---------------------------------------------------------------------------

def test_batcher_linger_coalesces_requests():
    fake = _FakeEngine(delay=0.0)
    b = DynamicBatcher(fake, max_linger_ms=120.0, max_queue=64)
    try:
        futs = [b.submit(np.full((1, 3), i, np.float32)) for i in range(4)]
        outs = [f.result(timeout=5)[0][0] for f in futs]
    finally:
        b.close()
    # all four coalesced into one engine call (linger window >> submit gap)
    assert [rows for rows, _t in fake.calls] == [4]
    for i, o in enumerate(outs):
        assert np.array_equal(o, np.full((1, 3), 2.0 * i))


def test_deadline_expired_requests_shed_not_executed():
    """A request whose deadline passes while the worker is busy is shed at
    assembly — the engine must never see it."""
    fake = _FakeEngine(delay=0.3)
    b = DynamicBatcher(fake, max_linger_ms=0.0, max_queue=64)
    try:
        slow = b.submit(np.ones((2, 3), np.float32))          # occupies worker
        time.sleep(0.05)  # ensure it was picked before the doomed one lands
        doomed = b.submit(np.ones((1, 3), np.float32), deadline_ms=100)
        slow.result(timeout=5)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=5)
    finally:
        b.close()
    assert [rows for rows, _t in fake.calls] == [2], \
        "expired request must be shed, not executed"
    # dead-on-arrival (negative budget, e.g. propagated from an upstream
    # hop that already blew it) is refused at submit
    with pytest.raises(DeadlineExceeded):
        DynamicBatcher(_FakeEngine(), max_linger_ms=0).submit(
            np.ones((1, 3), np.float32), deadline_ms=-1.0)


def test_tight_deadline_joining_mid_linger_caps_the_linger():
    """A tight-deadline request that joins a batch DURING linger must cap
    the remaining linger at its own deadline — otherwise the batch waits
    out the full window and executes it late (regression: the cap used to
    be computed only from the initial members)."""
    fake = _FakeEngine(delay=0.0, max_batch_size=8)
    b = DynamicBatcher(fake, max_linger_ms=500.0, max_queue=64)
    t0 = time.monotonic()
    try:
        a = b.submit(np.ones((1, 3), np.float32))            # opens linger
        time.sleep(0.05)
        tight = b.submit(np.ones((1, 3), np.float32), deadline_ms=100)
        a.result(timeout=5)
        try:
            tight.result(timeout=5)
            late = time.monotonic() - t0 > 0.25  # executed, but on time?
            assert not late, "tight request executed long past its deadline"
        except DeadlineExceeded:
            pass  # shed at the dispatch re-check: also within contract
    finally:
        b.close()
    # the batch must have dispatched near the tight deadline (~0.15s),
    # nowhere near the 0.5s linger window
    assert fake.calls and fake.calls[0][1] - t0 < 0.35, \
        f"linger was not capped by the joining deadline " \
        f"(dispatched at +{fake.calls[0][1] - t0:.3f}s)"


def test_priority_lane_beats_bulk_backlog():
    """With a bulk backlog queued, a tight-SLO (priority 0) request is
    dispatched in the very next batch — never behind remaining bulk."""
    fake = _FakeEngine(delay=0.15, max_batch_size=2)
    b = DynamicBatcher(fake, max_batch_size=2, max_linger_ms=0.0,
                       max_queue=64)
    order = []
    try:
        first = b.submit(np.full((2, 3), -1, np.float32), priority=1)
        time.sleep(0.05)  # worker now busy with `first`
        bulk = [b.submit(np.full((2, 3), i, np.float32), priority=1)
                for i in range(4)]
        urgent = b.submit(np.full((1, 3), 99, np.float32), priority=0)
        done = {}
        for name, f in [("first", first), ("urgent", urgent)] + \
                [(f"bulk{i}", f) for i, f in enumerate(bulk)]:
            f.result(timeout=10)
            done[name] = True
    finally:
        b.close()
    # engine call order: first, then urgent (lane 0), then the bulk queue
    vals = [rows for rows, _t in fake.calls]
    assert vals[0] == 2
    assert vals[1] == 1, f"urgent not dispatched next: row trace {vals}"


def test_queue_watermark_load_shedding():
    fake = _FakeEngine(delay=0.3)
    b = DynamicBatcher(fake, max_linger_ms=0.0, max_queue=3)
    try:
        b.submit(np.ones((1, 3), np.float32))   # in flight shortly
        time.sleep(0.05)
        kept = [b.submit(np.ones((1, 3), np.float32)) for _ in range(3)]
        with pytest.raises(RequestRejected):
            b.submit(np.ones((1, 3), np.float32))
        assert b.stats()["shed"] == 1
        for f in kept:
            f.result(timeout=5)
    finally:
        b.close()


def test_batcher_splits_results_exactly():
    net, arg = _mlp()
    # single bucket: a direct run and a coalesced run execute the same
    # program, so the split results must be bitwise identical
    engine = InferenceEngine(net, arg, buckets=(8,), lint="off")
    b = DynamicBatcher(engine, max_linger_ms=80.0)
    rng = np.random.RandomState(4)
    xs = [rng.rand(n, 6).astype(np.float32) for n in (1, 3, 2)]
    try:
        futs = [b.submit(x) for x in xs]
        outs = [f.result(timeout=10) for f in futs]
    finally:
        b.close()
    for x, (o, _version) in zip(xs, outs):
        assert np.array_equal(o[0], engine.predict(x)), \
            "coalesced result differs from a direct run"


# ---------------------------------------------------------------------------
# 3. hot reload
# ---------------------------------------------------------------------------

def test_hot_reload_old_or_new_never_mixed():
    """Concurrent traffic during repeated reloads: every output equals
    exactly scale_old*x or scale_new*x — a mixed-generation output would
    match neither."""
    net, arg, engine = _linear_engine(scale=1.0)
    engine.warmup((4,))
    scales = [1.0, 3.0]
    stop = threading.Event()
    bad = []
    rng = np.random.RandomState(5)
    xs = [rng.rand(n, 4).astype(np.float32) for n in (1, 2, 3, 4)]
    expected = {s: [x * np.float32(s) for x in xs] for s in scales}

    def traffic():
        i = 0
        while not stop.is_set():
            k = i % len(xs)
            out, ver = engine.infer([xs[k]])
            o = out[0]
            if not any(np.array_equal(o, expected[s][k]) for s in scales):
                bad.append((k, ver, o))
                return
            i += 1

    threads = [threading.Thread(target=traffic) for _ in range(4)]
    for t in threads:
        t.start()
    for gen in range(1, 9):
        s = scales[gen % 2]
        engine.reload({"fc_weight": np.eye(4, dtype=np.float32)
                       * np.float32(s)})
        time.sleep(0.01)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not bad, f"mixed/unknown-generation output observed: {bad[0]}"
    assert engine.version == 8
    # reloads must not have grown the program count
    assert engine.num_programs == len(engine.buckets)
    assert TraceLinter().check_serve_engine(engine) == []


def test_reload_rejects_aval_drift():
    _net, _arg, engine = _linear_engine(scale=1.0)
    with pytest.raises(ServeError, match="aval mismatch"):
        engine.reload({"fc_weight": np.eye(5, dtype=np.float32)})
    with pytest.raises(ServeError, match="missing"):
        engine.reload({})
    assert engine.version == 0  # failed reloads leave the old generation


# ---------------------------------------------------------------------------
# 4. endpoint: probes, drain, chaos, flagship end-to-end
# ---------------------------------------------------------------------------

def test_server_probes_drain_lifecycle():
    _net, _arg, engine = _linear_engine(scale=2.0)
    srv = ServeServer(engine, port=0, max_linger_ms=0.5)
    srv.start()
    cli = ServeClient("127.0.0.1", srv.port, retries=2)
    try:
        assert cli.health() and cli.ready()
        x = np.ones((2, 4), np.float32)
        out = cli.infer(x, deadline_ms=5000)
        assert np.array_equal(out, x * 2.0)
        st = cli.stats()
        assert st["engine"]["executions"] >= 1
        assert st["batcher"]["completed"] >= 1
        # drain: readiness flips, new work refused, probe still alive
        assert cli.drain()
        assert cli.health() and not cli.ready()
        with pytest.raises(Draining):
            cli.infer(x)
    finally:
        try:
            cli.shutdown()
        except ServeError:
            pass
        cli.close()
        srv.stop()


def test_chaos_drop_on_serve_socket_degrades_gracefully():
    """A dropped INFER reply (lost ack) and a dropped request frame both
    degrade to a client retry with the correct answer — inference is
    stateless, so at-least-once is safe. The injection lands in the same
    telemetry timeline as the retry."""
    _net, _arg, engine = _linear_engine(scale=2.0)
    srv = ServeServer(engine, port=0, max_linger_ms=0.0)
    srv.start()
    obs.enable()
    x = np.ones((1, 4), np.float32)
    try:
        chaos_rpc.configure([chaos_rpc.Rule("infer", "drop_reply", {1})])
        cli = ServeClient("127.0.0.1", srv.port, retries=3,
                          retry_interval=0.05)
        out = cli.infer(x)  # first reply dropped -> transparent retry
        assert np.array_equal(out, x * 2.0)
        cli.close()

        chaos_rpc.configure([chaos_rpc.Rule("infer", "drop_request", {1})])
        cli = ServeClient("127.0.0.1", srv.port, retries=3,
                          retry_interval=0.05)
        out = cli.infer(x)
        assert np.array_equal(out, x * 2.0)
        cli.close()
    finally:
        chaos_rpc.reset()
        srv.stop()
    snap = obs.metrics.snapshot()
    assert snap["counters"].get("chaos.injected", 0) >= 2
    assert snap["counters"].get("serve.client.retries", 0) >= 2
    names = {e[1] for e in obs.trace.events()}
    assert "chaos.rpc" in names and "serve.client.rpc" in names


def test_serve_flagship_end_to_end():
    """ISSUE 5 acceptance: model-zoo CNN, 2 training batches, checkpoint,
    serve.load, concurrent mixed-shape clients — outputs bitwise equal to
    direct Module.predict, program count ≤ buckets, chrome trace carries
    complete serve.* phase spans for every request."""
    import os
    import tempfile

    from mxnet_tpu.gluon.model_zoo import get_model

    mx.random.seed(7)
    np.random.seed(7)
    classes, img = 4, 16
    zoo = get_model("resnet18_v1", classes=classes, thumbnail=True)
    traced = zoo(sym.Variable("data"))
    net = sym.SoftmaxOutput(traced, name="softmax")

    rng = np.random.RandomState(7)
    x = rng.rand(8, 3, img, img).astype(np.float32)
    y = rng.randint(0, classes, 8).astype(np.float32)
    mod = Module(net, data_names=("data",), label_names=("softmax_label",))
    mod.fit(NDArrayIter(x, y, batch_size=4), num_epoch=1,  # 2 batches
            optimizer_params={"learning_rate": 0.05})
    tmp = tempfile.mkdtemp(prefix="mxtpu_serve_")
    prefix = os.path.join(tmp, "cnn")
    mod.save_checkpoint(prefix, 1)

    engine = serve.load(prefix, epoch=1, buckets=(2, 4), lint="warn")
    obs.enable()
    srv = ServeServer(engine, port=0, max_linger_ms=1.0)
    srv.start()

    # Module.predict oracles, one per bucket: the engine's bucket-B
    # program is the SAME executable predict runs at batch B (identical
    # jaxpr — see engine.py), so a size-n request padded to bucket B must
    # be bitwise equal to the batch-B predict of the same rows
    qx = rng.rand(14, 3, img, img).astype(np.float32)
    ref = {b: mod.predict(NDArrayIter(qx, None, batch_size=b)).asnumpy()
           for b in (2, 4)}

    sizes = [1, 2, 3, 4, 1, 3]  # mixed ragged shapes across threads
    offsets = np.cumsum([0] + sizes)
    results = {}
    errors = []

    def client_thread(i):
        try:
            cli = ServeClient("127.0.0.1", srv.port)
            lo, hi = offsets[i], offsets[i] + sizes[i]
            out, ver = cli.infer(qx[lo:hi], deadline_ms=60000,
                                 priority=i % 2, return_version=True)
            results[i] = (out, ver)
            cli.close()
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=client_thread, args=(i,))
               for i in range(len(sizes))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    srv.stop()
    assert not errors, f"client failures: {errors}"

    for i, size in enumerate(sizes):
        out, ver = results[i]
        lo = offsets[i]
        assert ver == 0
        # the request executed in bucket 2 or 4 (depending on which
        # concurrent requests it coalesced with) — its rows must be
        # bitwise equal to the matching-batch Module.predict oracle
        assert any(np.array_equal(out, ref[b][lo:lo + size])
                   for b in (2, 4)), \
            f"thread {i} (rows {lo}:{lo + size}) not bitwise equal to " \
            "Module.predict at either bucket"

    # program bound: ≤ one compiled program per shape bucket, proven by
    # the engine log AND the linter rule
    assert engine.num_programs <= len(engine.buckets) == 2
    assert TraceLinter().check_serve_engine(engine) == []

    # chrome trace: complete serve.* phase spans for every request
    trace_path = os.path.join(tmp, "serve_trace.json")
    obs.export(trace_path)
    with open(trace_path) as f:
        doc = json.load(f)
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"
             and str(e.get("name", "")).startswith("serve.")]
    by_name = {}
    for e in spans:
        by_name.setdefault(e["name"], []).append(e)
    for phase in ("serve.queue_wait", "serve.batch_assembly",
                  "serve.execute", "serve.serialize", "serve.rpc"):
        assert phase in by_name, f"missing {phase} spans: {sorted(by_name)}"
        assert all("dur" in e for e in by_name[phase])
    # one queue_wait span per request, one rpc span per wire call
    assert len(by_name["serve.queue_wait"]) == len(sizes)
    assert len(by_name["serve.rpc"]) >= len(sizes)
    # latency histogram made it into the exported metrics snapshot
    hists = doc["otherData"]["metrics"]["histograms"]
    assert "serve.latency_seconds" in hists
    assert hists["serve.latency_seconds"]["count"] == len(sizes)


def test_server_hot_reload_over_the_wire():
    """RELOAD RPC: server swaps onto a newer checkpoint; replies carry the
    new version; in-flight/old results stay self-consistent."""
    import os
    import tempfile

    net, arg = _mlp()
    tmp = tempfile.mkdtemp(prefix="mxtpu_reload_")
    prefix = os.path.join(tmp, "m")
    from mxnet_tpu.model import save_checkpoint

    save_checkpoint(prefix, 0, net, {k: nd.array(v) for k, v in arg.items()},
                    {})
    arg2 = {k: v + np.float32(0.25) for k, v in arg.items()}
    save_checkpoint(prefix, 1, net, {k: nd.array(v) for k, v in arg2.items()},
                    {})

    engine = serve.load(prefix, epoch=0, max_batch_size=4, lint="off")
    srv = ServeServer(engine, port=0, max_linger_ms=0.0)
    srv.start()
    cli = ServeClient("127.0.0.1", srv.port)
    rng = np.random.RandomState(8)
    x = rng.rand(2, 6).astype(np.float32)
    try:
        out0, v0 = cli.infer(x, return_version=True)
        assert v0 == 0
        new_version = cli.reload(prefix, epoch=1)
        assert new_version == 1
        out1, v1 = cli.infer(x, return_version=True)
        assert v1 == 1
        assert not np.array_equal(out0, out1)
        # old-or-new proof at the engine level: out1 equals a fresh engine
        # loaded directly from epoch 1
        direct = serve.load(prefix, epoch=1, max_batch_size=4,
                            lint="off").predict(x)
        assert np.array_equal(out1, direct)
    finally:
        cli.close()
        srv.stop()


def test_serve_load_checkpoint_dir_and_symbol_required():
    import os
    import tempfile

    net, arg = _mlp()
    tmp = tempfile.mkdtemp(prefix="mxtpu_ckdir_")
    ckdir = os.path.join(tmp, "ck")
    rng = np.random.RandomState(9)
    x = rng.rand(8, 6).astype(np.float32)
    y = rng.randint(0, 4, 8).astype(np.float32)
    mod = Module(net, data_names=("data",), label_names=("softmax_label",))
    mod.fit(NDArrayIter(x, y, batch_size=4), num_epoch=1,
            optimizer_params={"learning_rate": 0.1}, checkpoint=ckdir)
    with pytest.raises(ServeError, match="symbol"):
        serve.load(ckdir)
    engine = serve.load(ckdir, symbol=net, max_batch_size=4, lint="off")
    ref = mod.predict(NDArrayIter(x[:3], None, batch_size=3)).asnumpy()
    assert np.array_equal(engine.predict(x[:3]), ref)


def test_gluon_export_serves_bitwise():
    """HybridBlock.export now embeds the traced graph + param map, so the
    export is directly servable and bitwise-faithful to the block."""
    import os
    import tempfile

    from mxnet_tpu import gluon

    mx.random.seed(10)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(12, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    rng = np.random.RandomState(10)
    x = rng.rand(5, 7).astype(np.float32)
    ref = net(nd.array(x)).asnumpy()
    tmp = tempfile.mkdtemp(prefix="mxtpu_gluon_")
    path = os.path.join(tmp, "dense")
    net.export(path, epoch=0)
    with open(f"{path}-symbol.json") as f:
        meta = json.load(f)
    assert "symbol" in meta and "param_map" in meta
    engine = serve.load(path, epoch=0, max_batch_size=8, lint="off")
    assert np.array_equal(engine.predict(x), ref)


def test_symbol_json_roundtrip_preserves_aux_states():
    """Regression (found by the serve-load path): tojson drops internal
    ``__`` attrs, so auxness must be re-derived on load from the op
    registry's aux slot names — otherwise a reloaded BatchNorm checkpoint
    rebinds its moving stats as plain zero-initialized arguments and
    serves wrong (and Module.load silently evals wrong, too)."""
    data = sym.Variable("data")
    net = sym.BatchNorm(
        sym.Convolution(data, num_filter=4, kernel=(3, 3), pad=(1, 1),
                        name="c"), name="bn")
    net = sym.SoftmaxOutput(sym.FullyConnected(sym.Flatten(net),
                                               num_hidden=3, name="fc"),
                            name="softmax")
    loaded = mx.sym.load_json(net.tojson())
    assert loaded.list_auxiliary_states() == net.list_auxiliary_states()
    assert loaded.list_arguments() == net.list_arguments()

    # end-to-end: a served checkpoint of a symbolic-BN model is bitwise
    # faithful to the live module (moving stats actually restored)
    import os
    import tempfile

    rng = np.random.RandomState(11)
    x = rng.rand(8, 3, 8, 8).astype(np.float32)
    y = rng.randint(0, 3, 8).astype(np.float32)
    mod = Module(net, data_names=("data",), label_names=("softmax_label",))
    mod.fit(NDArrayIter(x, y, batch_size=4), num_epoch=1,
            optimizer_params={"learning_rate": 0.05})
    prefix = os.path.join(tempfile.mkdtemp(prefix="mxtpu_aux_"), "bn")
    mod.save_checkpoint(prefix, 1)
    engine = serve.load(prefix, epoch=1, buckets=(4,), lint="off")
    ref = mod.predict(NDArrayIter(x[:4], None, batch_size=4)).asnumpy()
    assert np.array_equal(engine.predict(x[:4]), ref)


def test_engine_rejects_missing_weights():
    """A checkpoint missing (or misnaming) a WEIGHT must be refused at
    load — zero-filling it would serve wrong predictions silently (only
    label-like training-head leftovers may be zero-filled)."""
    net, arg = _mlp()
    bad = dict(arg)
    del bad["fc2_weight"]
    with pytest.raises(ServeError, match="fc2_weight"):
        InferenceEngine(net, bad, lint="off")
