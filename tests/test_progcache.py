"""Persistent AOT program cache (mxnet_tpu/progcache.py,
docs/PERFORMANCE.md "Program cache and cold start").

- key derivation: one shared ``program_key`` — deterministic across
  processes, distinct across models/statics, canonicalization units;
- structure: hit / miss / reject (truncated entry, CRC corruption,
  foreign-platform fingerprint, stale-code fingerprint) — every bad entry
  degrades to a plain compile with a counted reject, never a crash;
- bitwise parity: a cache-hit engine answers bit-for-bit what the
  fresh-compile engine answered (serve buckets AND the fused update);
- bounds kept: TraceLinter's serve program bound stays green on hits, the
  fused update still dispatches one program per step;
- artifact payloads: ``serve.ship_programs`` → ``serve.load`` warms from
  the shipped ``programs/`` dir;
- elastic-rejoin prewarm: a checkpoint-derived ``prewarm_batch`` derives
  the SAME key a real fit's engine uses (hit, not a wasted compile);
- keep-last-N GC;
- the chaos leg (slow): a ProcReplica SIGKILLed and respawned against the
  same cache dir becomes ready with zero fresh XLA compiles.
"""
import os
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler, progcache
from mxnet_tpu import optimizer as opt_mod
from mxnet_tpu import serve
from mxnet_tpu import symbol as sym

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

pytestmark = pytest.mark.progcache


@pytest.fixture()
def cache_dir(tmp_path):
    """Arm the process-global cache at a per-test dir; disarm after."""
    d = str(tmp_path / "progcache")
    progcache.configure(d)
    yield d
    progcache.configure(None)
    os.environ.pop("MXNET_PROGCACHE_DIR", None)
    os.environ.pop("MXNET_PROGCACHE", None)
    progcache.reset()


def _mlp(hidden=8, in_dim=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = sym.softmax(net, name="prob")
    rng = np.random.RandomState(0)
    arg = {"fc1_weight": rng.randn(hidden, in_dim).astype(np.float32) * 0.3,
           "fc1_bias": rng.randn(hidden).astype(np.float32)}
    return net, arg


def _engine(net, arg, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("lint", "off")
    return serve.InferenceEngine(net, arg, {}, **kw)


# ---------------------------------------------------------------------------
# key derivation
# ---------------------------------------------------------------------------

def test_program_key_deterministic_and_distinct():
    statics = ((b"graph", ("data",), 0.0),
               {"b": 1, "a": 2}, float("0.1"), type(int))
    k1 = progcache.program_key("serve", "bucket4", statics)
    k2 = progcache.program_key("serve", "bucket4", statics)
    assert k1 == k2 and k1.site == "serve" and k1.label == "bucket4"
    assert len(k1.digest) == 64
    # any drift in site/label/statics changes the digest
    assert progcache.program_key("update", "bucket4", statics) != k1
    assert progcache.program_key("serve", "bucket8", statics) != k1
    assert progcache.program_key(
        "serve", "bucket4", ((b"graph2", ("data",), 0.0),)) != k1
    # dict ordering canonicalizes away
    assert progcache.program_key("s", "l", {"a": 1, "b": 2}) \
        == progcache.program_key("s", "l", {"b": 2, "a": 1})


def test_env_fingerprint_fields():
    fp = progcache.env_fingerprint()
    for field in ("platform", "device_kind", "num_devices", "jax",
                  "jaxlib", "code"):
        assert field in fp, fp
    assert fp["platform"] == "cpu"
    # cached copy is defensive — mutating it must not poison the source
    fp["platform"] = "mars"
    assert progcache.env_fingerprint()["platform"] == "cpu"


# ---------------------------------------------------------------------------
# hit / miss / reject structure
# ---------------------------------------------------------------------------

def _put_one(cache, tag="x", shape=(3, 2)):
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: x * 2.0)
    compiled = fn.lower(jnp.zeros(shape)).compile()
    key = progcache.program_key("test", tag, (tag, shape))
    assert cache.put(key, compiled, meta={"bucket": 1})
    return key, compiled


def test_roundtrip_hit_and_miss(tmp_path):
    cache = progcache.ProgramCache(str(tmp_path))
    key, _ = _put_one(cache)
    assert cache.stats["write"] == 1
    miss = progcache.program_key("test", "other", ("other",))
    assert cache.get(miss) is None
    assert cache.stats["miss"] == 1
    entry = cache.get(key)
    assert entry is not None and entry.meta["bucket"] == 1
    assert cache.stats["hit"] == 1 and cache.stats["reject"] == 0
    import jax.numpy as jnp

    out = entry.executable(jnp.ones((3, 2)))
    np.testing.assert_array_equal(np.asarray(out), np.full((3, 2), 2.0))


def test_truncated_entry_rejects(tmp_path):
    cache = progcache.ProgramCache(str(tmp_path))
    key, _ = _put_one(cache)
    path = cache._path(key.digest)
    with open(path, "rb") as f:
        raw = f.read()
    with open(path, "wb") as f:
        f.write(raw[: len(raw) // 2])
    assert cache.get(key) is None
    assert cache.stats["reject"] == 1 and cache.stats["hit"] == 0


def test_corrupt_byte_rejects(tmp_path):
    cache = progcache.ProgramCache(str(tmp_path))
    key, _ = _put_one(cache)
    path = cache._path(key.digest)
    with open(path, "r+b") as f:
        f.seek(len(progcache._MAGIC) + 30)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    assert cache.get(key) is None
    assert cache.stats["reject"] == 1


def test_foreign_fingerprint_rejects(tmp_path):
    cache = progcache.ProgramCache(str(tmp_path))
    real = progcache.env_fingerprint()
    try:
        # entry written by a "TPU process with other code"
        progcache._env_fp_cache[0] = dict(real, platform="tpu",
                                          code="f" * 64)
        key, _ = _put_one(cache)
    finally:
        progcache._env_fp_cache[0] = dict(real)
    assert cache.get(key) is None, \
        "a foreign-platform executable must never load"
    assert cache.stats["reject"] == 1


def test_wrong_digest_filename_rejects(tmp_path):
    cache = progcache.ProgramCache(str(tmp_path))
    key, _ = _put_one(cache)
    other = progcache.program_key("test", "other", ("other",))
    os.rename(cache._path(key.digest), cache._path(other.digest))
    assert cache.get(other) is None  # header digest disagrees with name
    assert cache.stats["reject"] == 1


def test_gc_keep_last_n(tmp_path):
    cache = progcache.ProgramCache(str(tmp_path), keep=2)
    keys = []
    for i in range(4):
        k, _ = _put_one(cache, tag=f"t{i}", shape=(i + 1, 2))
        keys.append(k)
        # strict mtime ordering even on coarse-grained filesystems
        stamp = time.time() - 100 + i
        os.utime(cache._path(k.digest), (stamp, stamp))
    cache.gc()
    assert cache.entries() <= 2
    # the most recently used survives, the oldest is gone
    assert cache.get(keys[-1]) is not None
    assert cache.get(keys[0]) is None


# ---------------------------------------------------------------------------
# serve engine integration
# ---------------------------------------------------------------------------

def test_engine_cache_hit_bitwise_parity(cache_dir):
    net, arg = _mlp()
    e1 = _engine(net, arg)
    assert e1.warmup((4,)) == len(e1.buckets)
    assert all(e.get("cache_hit") is False for e in e1.compile_log)
    x = np.random.RandomState(3).rand(3, 4).astype(np.float32)
    ref = e1.predict(x)

    e2 = _engine(net, arg)
    assert e2.warmup((4,)) == len(e2.buckets)
    assert [e.get("cache_hit") for e in e2.compile_log] \
        == [True] * len(e2.buckets), "warm engine must hit every bucket"
    assert e2.cache_hits == len(e2.buckets)
    out = e2.predict(x)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out)), \
        "a deserialized executable is the same machine code — bitwise"
    # the program bound is still proven, hits included
    from mxnet_tpu.analysis.trace import TraceLinter

    assert TraceLinter().check_serve_engine(e1) == []
    assert TraceLinter().check_serve_engine(e2) == []
    # compile_log entries carry the shared program_key digest
    assert all(len(e.get("program_key", "")) == 64
               for e in e1.compile_log + e2.compile_log)
    # concurrent warmup logs buckets in completion order — compare as sets
    assert {e["program_key"] for e in e1.compile_log} \
        == {e["program_key"] for e in e2.compile_log}


def test_engine_key_drift_misses_not_collides(cache_dir):
    net, arg = _mlp(hidden=8)
    e1 = _engine(net, arg)
    e1.warmup((4,))
    # a DIFFERENT graph with identical input avals must not hit
    net2, arg2 = _mlp(hidden=6)
    e2 = _engine(net2, arg2)
    e2.warmup((4,))
    assert all(e.get("cache_hit") is False for e in e2.compile_log)
    # so must a changed engine static (pad value)
    e3 = _engine(net, arg, pad_value=1.0)
    e3.warmup((4,))
    assert all(e.get("cache_hit") is False for e in e3.compile_log)


def test_corrupt_cache_degrades_to_compile(cache_dir):
    net, arg = _mlp()
    e1 = _engine(net, arg)
    e1.warmup((4,))
    for f in os.listdir(cache_dir):
        if f.endswith(".mxprog"):
            path = os.path.join(cache_dir, f)
            with open(path, "r+b") as fh:
                fh.seek(20)
                fh.write(b"\xde\xad\xbe\xef")
    e2 = _engine(net, arg)
    assert e2.warmup((4,)) == len(e2.buckets)  # served anyway
    assert all(e.get("cache_hit") is False for e in e2.compile_log)
    assert e2._progcache.stats["reject"] >= len(e2.buckets)
    x = np.random.RandomState(3).rand(2, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(e1.predict(x)),
                               np.asarray(e2.predict(x)), rtol=0, atol=0)


def test_warmup_concurrent_matches_serial(cache_dir):
    net, arg = _mlp()
    e_serial = _engine(net, arg, max_batch_size=8)
    assert e_serial.warmup((4,), concurrency=1) == len(e_serial.buckets)
    e_conc = _engine(net, arg, max_batch_size=8, progcache_dir=None)
    # fresh dir so concurrency exercises the compile path, not hits
    e_conc._progcache = progcache.ProgramCache(cache_dir + "-conc")
    e_conc._key_statics = e_conc._compute_key_statics()
    assert e_conc.warmup((4,), concurrency=4) == len(e_conc.buckets)
    sigs = [e["sig"] for e in e_conc.compile_log]
    assert len(set(map(repr, sigs))) == len(sigs) == len(e_conc.buckets)
    from mxnet_tpu.analysis.trace import TraceLinter

    assert TraceLinter().check_serve_engine(e_conc) == []
    x = np.random.RandomState(5).rand(6, 4).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(e_serial.predict(x)),
                                  np.asarray(e_conc.predict(x)))


def test_warmup_idempotent_second_call_zero(cache_dir):
    net, arg = _mlp()
    e = _engine(net, arg)
    assert e.warmup((4,)) == len(e.buckets)
    assert e.warmup((4,)) == 0  # already-compiled buckets skip entirely


def test_ship_programs_and_load(cache_dir, tmp_path):
    # build + warm WITHOUT the global cache, then ship the payload
    progcache.configure(None)
    net, arg = _mlp()
    prefix = str(tmp_path / "model")
    mx.model.save_checkpoint(prefix, 0, net,
                             {k: nd.array(v) for k, v in arg.items()}, {})
    e1 = _engine(net, arg)
    e1.warmup((4,))
    n = serve.ship_programs(e1, prefix)
    assert n == len(e1.buckets)
    assert os.path.isdir(serve.programs_dir_for(prefix))
    eng = serve.load(prefix, epoch=0, max_batch_size=4, lint="off")
    assert eng._progcache is not None \
        and eng._progcache.root == serve.programs_dir_for(prefix)
    assert eng.warmup((4,)) == len(eng.buckets)
    assert all(e.get("cache_hit") for e in eng.compile_log)
    x = np.random.RandomState(7).rand(3, 4).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(e1.predict(x)),
                                  np.asarray(eng.predict(x)))


# ---------------------------------------------------------------------------
# fused update engine integration
# ---------------------------------------------------------------------------

def _one_step(cache_hit_expected, seed=42):
    rng = np.random.RandomState(seed)
    opt = opt_mod.create("adam", learning_rate=0.05, rescale_grad=0.5)
    up = opt_mod.Updater(opt)
    ws = [nd.array(rng.randn(5, 4).astype(np.float32)),
          nd.array(rng.randn(3).astype(np.float32))]
    gs = [nd.array(rng.randn(5, 4).astype(np.float32)),
          nd.array(rng.randn(3).astype(np.float32))]
    up.update_batch([0, 1], gs, ws)
    eng = up._engine
    assert eng is not None and len(eng.compile_log) == 1
    assert eng.compile_log[0].get("cache_hit") is cache_hit_expected
    assert len(eng.compile_log[0].get("program_key", "")) == 64
    return [w.asnumpy() for w in ws], up


def test_fused_cache_hit_bitwise_and_dispatch_bound(cache_dir):
    w_fresh, _ = _one_step(cache_hit_expected=False)
    w_hit, up = _one_step(cache_hit_expected=True)
    for a, b in zip(w_fresh, w_hit):
        np.testing.assert_array_equal(a, b), \
            "cache-hit update must be bitwise the fresh-compile update"
    # the one-program-per-step bound holds on the deserialized executable
    rng = np.random.RandomState(1)
    ws = [nd.array(rng.randn(5, 4).astype(np.float32)),
          nd.array(rng.randn(3).astype(np.float32))]
    gs = [w.zeros_like() for w in ws]
    with profiler.count_dispatches() as c:
        up.update_batch([0, 1], gs, ws)
    assert c.total_compiled <= 2, c.as_dict()


def test_updater_prewarm_populates_without_mutating(cache_dir):
    rng = np.random.RandomState(0)
    opt = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9)
    up = opt_mod.Updater(opt)
    ws = [nd.array(rng.randn(4, 3).astype(np.float32))]
    before = ws[0].asnumpy().copy()
    assert up.prewarm_batch([0], ws)
    np.testing.assert_array_equal(before, ws[0].asnumpy())
    assert opt._index_update_count == {}, "prewarm must not advance counts"
    assert up._engine.compile_log[-1].get("cache_hit") is False
    # a second updater (the restarted worker) hits from disk
    opt2 = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9)
    up2 = opt_mod.Updater(opt2)
    ws2 = [nd.array(rng.randn(4, 3).astype(np.float32))]
    assert up2.prewarm_batch([0], ws2)
    assert up2._engine.compile_log[-1].get("cache_hit") is True


def test_module_fit_then_checkpoint_prewarm_hits(cache_dir, tmp_path):
    """The elastic-rejoin warm path derives the SAME program key from the
    shared checkpoint that the live fit's engine derives from its bound
    executor — so a quarantined rejoiner's prewarm is a cache HIT."""
    from mxnet_tpu.checkpoint import as_manager
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.module import Module

    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fc1")
    net = sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    x = rng.randn(8, 5).astype(np.float32)
    y = np.array([0, 1, 2, 3, 0, 1, 2, 3], np.float32)
    it = NDArrayIter(x, y, batch_size=4)
    ckpt = str(tmp_path / "ckpt")
    mod = Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            checkpoint=ckpt, resume="never")
    pc = progcache.cache()
    writes = pc.stats["write"]
    assert writes >= 1

    mod2 = Module(net, context=mx.cpu())
    mgr = as_manager(ckpt)
    try:
        hits_before = pc.stats["hit"]
        assert mod2._prewarm_update_programs(
            mgr, "sgd", {"learning_rate": 0.1, "momentum": 0.9}, it)
        assert pc.stats["hit"] == hits_before + 1, \
            "checkpoint-derived prewarm must hit the fit's cached program"
        assert pc.stats["write"] == writes  # nothing recompiled
    finally:
        mgr.close()


# ---------------------------------------------------------------------------
# the chaos leg: replica SIGKILL → respawn warms from disk
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.chaos
def test_proc_replica_restart_warms_from_cache(tmp_path):
    import serve_bench

    res = serve_bench.run_cold_bench(model="mlp", max_batch_size=4,
                                     keep_artifact=str(tmp_path))
    assert res["ok"], res
    assert res["fresh_compiles_cold"] == 3  # buckets(4) = [1, 2, 4]
    assert res["fresh_compiles_warm"] == 0
    assert res["cache_hits_warm"] == res["compiles_warm"] == 3
