"""Elastic-training worker for the flagship chaos test (tests/test_elastic.py).

Trains a small linear-regression Module through the REAL ``Module.fit``
elastic path: generation-scoped gradient sync over the PS wire, shard
recuts at epoch boundaries, shared-checkpoint rejoin. The harness SIGKILLs
one of these mid-epoch (on a ``CHAOS_STEP`` marker), restarts it, and
asserts the fleet's run-to-completion loss matches an uninjected run
within documented tolerance (docs/ROBUSTNESS.md "Elastic training").

Markers on stdout (the orchestration contract):
    CHAOS_STEP <n>          after every optimizer step
    EPOCH_START <e> parts=<p>  at the first batch of each epoch
    FINAL_LOSS <mse>        full-train-set MSE after the last epoch
    elastic_worker rank <r>: OK
"""
import argparse
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_data(seed: int, samples: int):
    """Deterministic synthetic regression problem — identical on every
    rank (the iterator's shard recut slices it per assignment)."""
    rng = np.random.RandomState(seed)
    x = rng.randn(samples, 16).astype(np.float32)
    w = rng.randn(16, 1).astype(np.float32)
    y = (x @ w).ravel() + 0.01 * rng.randn(samples).astype(np.float32)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--batch-size", type=int, default=8)
    # divisible by batch_size * parts for parts in {1,2,3}: every live
    # fleet size cuts to EQUAL whole-batch shards (lockstep reduce rounds
    # require equal per-worker batch counts — documented constraint)
    ap.add_argument("--samples", type=int, default=96)
    ap.add_argument("--step-delay", type=float, default=0.0,
                    help="sleep per step — the chaos test stretches epochs "
                    "so a restarted worker (~seconds of interpreter+jax "
                    "startup) rejoins a fleet that is still mid-training")
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu.io import NDArrayIter

    # create-kvstore-first ordering; elastic via MXNET_ELASTIC=1 +
    # MXNET_PS_ADDR/PORT in the environment (set by the test harness)
    kv = mx.kv.create("dist_sync")
    rank = kv.rank

    x, y = make_data(args.seed, args.samples)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("lin_label")
    fc = mx.sym.FullyConnected(data, num_hidden=1, name="fc")
    out = mx.sym.LinearRegressionOutput(fc, label, name="lro")
    mod = mx.mod.Module(out, data_names=("data",), label_names=("lin_label",))

    it = NDArrayIter({"data": x}, {"lin_label": y},
                     batch_size=args.batch_size, shuffle=False,
                     label_name="lin_label")

    state = {"step": 0, "epoch": None}

    def on_batch(param):
        if param.epoch != state["epoch"]:
            state["epoch"] = param.epoch
            print(f"EPOCH_START {param.epoch} parts={it.num_parts}",
                  flush=True)
        state["step"] += 1
        print(f"CHAOS_STEP {state['step']}", flush=True)
        if args.step_delay:
            import time

            time.sleep(args.step_delay)

    mod.fit(it, num_epoch=args.epochs, kvstore=kv, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05,
                              "rescale_grad": 1.0 / args.batch_size},
            eval_metric="mse", checkpoint=args.ckpt_dir, resume="auto",
            checkpoint_period=1, batch_end_callback=on_batch,
            handle_preemption=False)

    full = NDArrayIter({"data": x}, {"lin_label": y},
                       batch_size=args.batch_size, label_name="lin_label")
    loss = dict(mod.score(full, "mse"))["mse"]
    print(f"FINAL_LOSS {loss:.6f}", flush=True)
    kv.close()
    print(f"elastic_worker rank {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
