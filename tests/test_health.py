"""Training-health plane suite (``pytest -m health`` / ``make health``).

Covers the plane's contracts (docs/OBSERVABILITY.md "Training health"):

1. sentinel detectors over synthetic series — loss spike (EWMA-judged),
   grad-norm explosion, plateau (warn-only), scaler skip streak
   (warn-once + breach), non-finite (fatal), the warn → lr-backoff →
   rollback escalation ladder, rollback cooldown/cap suppression;
2. the dispatch-bound proof — the in-graph stats add ZERO extra program
   executions on a sampled step (one batched d2h fetch only) and exactly
   nothing when the plane is off;
3. deterministic NaN chaos (``MXNET_CHAOS_NAN`` / chaos/nan.py) —
   occurrence counting, the provenance blame pass naming the first
   non-finite node, rollback-target selection skipping poisoned
   checkpoints;
4. the flagship — NaN injected mid-epoch into a checkpointed Module.fit:
   sentinel breach → blame names the op → auto-rollback → the resumed
   segment is bitwise-identical to an uninjected run, and the whole story
   (counter tracks, breach, provenance, rollback) renders in one chrome
   trace via tools/trace_report.py;
5. integration satellites — gluon Trainer attach (skip-streak breach
   through a real AMP scaler), estimator HealthHandler, Monitor-as-
   adapter gauges.
"""
import math
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import obs, profiler
from mxnet_tpu import symbol as sym
from mxnet_tpu.chaos import nan as nan_chaos
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import Module
from mxnet_tpu.obs import health as health_mod
from mxnet_tpu.obs.health import HealthMonitor

pytestmark = pytest.mark.health

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _clean():
    """Telemetry + chaos + health module state reset around every test."""
    obs.disable()
    obs.reset()
    nan_chaos.reset()
    health_mod.request_stats(None)
    health_mod._ACTIVE[0] = 0
    yield
    obs.disable()
    obs.reset()
    nan_chaos.reset()
    health_mod.request_stats(None)
    health_mod._ACTIVE[0] = 0


class _FakeEngine:
    """A stand-in engine whose ``last_health`` holds HOST values — the
    monitor's batched fetch passes them through untouched, so detectors
    can be unit-tested on synthetic series with no device work."""

    def __init__(self, gnorm, nonfinite=(0, 0), streak=None):
        n = len(nonfinite)
        self.last_health = {
            "global_grad_norm": np.float32(gnorm),
            "grad_norms": np.full(n, gnorm / max(n, 1), np.float32),
            "param_norms": np.ones(n, np.float32),
            "update_norms": np.full(n, 1e-3, np.float32),
            "nonfinite": np.asarray(nonfinite, np.int32),
            "indices": tuple(range(n)),
        }
        if streak is not None:
            self.last_health["skip_streak"] = np.int32(streak)


# ---------------------------------------------------------------------------
# sentinel detectors on synthetic series
# ---------------------------------------------------------------------------

def test_loss_spike_detector_judges_against_prior_ewma():
    mon = HealthMonitor(every=1, loss_spike=3.0)
    for i in range(5):
        rep = mon.step(i, loss=1.0 + 0.01 * i)
        assert rep["ok"], rep
    rep = mon.step(6, loss=50.0)
    rules = [b["rule"] for b in rep["breaches"]]
    assert rules == ["loss_spike"]
    # the spike did NOT inflate its own baseline: EWMA still near 1
    assert rep["loss_ewma"] < 2.0


def test_grad_norm_explosion_detector():
    mon = HealthMonitor(every=1, grad_explosion=10.0)
    for i in range(4):
        rep = mon.step(i, engine=_FakeEngine(gnorm=1.0))
        assert rep["ok"]
    rep = mon.step(5, engine=_FakeEngine(gnorm=500.0))
    assert [b["rule"] for b in rep["breaches"]] == ["grad_norm_explosion"]


def test_plateau_detector_is_warn_only():
    mon = HealthMonitor(every=1, plateau_window=6, plateau_eps=1e-3,
                        actions="rollback")
    rep = None
    for i in range(6):
        rep = mon.step(i, loss=1.0)
    assert [b["rule"] for b in rep["breaches"]] == ["plateau"]
    assert rep["action"] == "warn"  # advice, never an emergency
    # re-arms over a fresh window: next sample does not re-breach
    assert mon.step(7, loss=1.0)["ok"]


def test_decreasing_loss_never_plateaus_or_spikes():
    mon = HealthMonitor(every=1, plateau_window=8)
    for i in range(30):
        rep = mon.step(i, loss=2.0 * 0.9 ** i,
                       engine=_FakeEngine(gnorm=1.0 + 0.01 * i))
        assert rep["ok"], rep["breaches"]


def test_nonfinite_is_fatal_and_names_worst_param():
    mon = HealthMonitor(every=1, actions="rollback",
                        param_names=["fc1_weight", "fc1_bias"])
    rep = mon.step(1, engine=_FakeEngine(gnorm=float("nan"),
                                         nonfinite=(7, 0)))
    assert [b["rule"] for b in rep["breaches"]] == ["nonfinite"]
    assert rep["action"] == "rollback"  # fatal jumps the ladder
    assert rep["breaches"][0]["param"] == "fc1_weight"


def test_skip_streak_breach_and_warn_once():
    mon = HealthMonitor(every=1, skip_streak_threshold=3)
    warned = []
    mon.logger = type("L", (), {"warning": lambda self, *a: warned.append(a)})()
    assert mon.step(1, engine=_FakeEngine(gnorm=1.0, streak=1))["ok"]
    rep = mon.step(2, engine=_FakeEngine(gnorm=1.0, streak=4))
    assert [b["rule"] for b in rep["breaches"]] == ["scaler_skip_streak"]
    n_after_first = len(warned)
    mon.step(3, engine=_FakeEngine(gnorm=1.0, streak=5))
    # the dedicated warn-once fired exactly once for the ongoing streak
    # (each sampled breach still logs its own one-line summary)
    once = [w for w in warned if "skip streak reached" in str(w[0])]
    assert len(once) == 1 and n_after_first >= 1


def test_escalation_ladder_warn_backoff_rollback():
    mon = HealthMonitor(every=1, loss_spike=2.0, actions="rollback",
                        rollback_cooldown=0)
    for i in range(4):
        mon.step(i, loss=1.0)
    actions = []
    for i in range(3):
        rep = mon.step(10 + i, loss=100.0 * (3 ** i))
        actions.append(rep["action"])
    assert actions == ["warn", "lr_backoff", "rollback"]


def test_ladder_capped_by_actions_ceiling():
    mon = HealthMonitor(every=1, loss_spike=2.0, actions="warn")
    for i in range(4):
        mon.step(i, loss=1.0)
    for i in range(4):
        rep = mon.step(10 + i, loss=1000.0 * (3 ** i))
    assert rep["action"] == "warn"


def test_rollback_cooldown_and_cap_suppress():
    mon = HealthMonitor(every=1, actions="rollback", rollback_cooldown=100,
                        max_rollbacks=2)
    rep = mon.step(10, engine=_FakeEngine(gnorm=1.0, nonfinite=(3,)))
    assert rep["action"] == "rollback"
    mon.note_rollback(10)
    # within cooldown: downgraded with an explicit note
    rep = mon.step(20, engine=_FakeEngine(gnorm=1.0, nonfinite=(3,)))
    assert rep["action"] == "warn" and "cooldown" in rep["note"]
    mon.note_rollback(200)  # second (and last allowed) rollback
    rep = mon.step(400, engine=_FakeEngine(gnorm=1.0, nonfinite=(3,)))
    assert rep["action"] == "warn" and "cap" in rep["note"]


def test_lr_backoff_applies_to_optimizer():
    from mxnet_tpu.optimizer import create as opt_create

    opt = opt_create("sgd", learning_rate=0.1)
    mon = HealthMonitor(every=1, loss_spike=2.0, actions="lr_backoff")
    for i in range(4):
        mon.step(i, loss=1.0, optimizer=opt)
    mon.step(10, loss=100.0, optimizer=opt)           # warn
    rep = mon.step(11, loss=1000.0, optimizer=opt)    # lr_backoff
    assert rep["action"] == "lr_backoff"
    assert math.isclose(opt.learning_rate, 0.05)


def test_on_breach_callbacks_fire_and_cannot_break_training():
    seen = []
    mon = HealthMonitor(every=1).on_breach(
        lambda rep, br: seen.append(br)).on_breach(
        lambda rep, br: 1 / 0)  # a broken pager hook must be swallowed
    mon.step(1, engine=_FakeEngine(gnorm=1.0, nonfinite=(1,)))
    assert len(seen) == 1 and seen[0][0]["rule"] == "nonfinite"


def test_sampling_period_and_will_sample():
    mon = HealthMonitor(every=4)
    outs = []
    for i in range(8):
        assert mon.will_sample() == ((i + 1) % 4 == 0)
        outs.append(mon.step(i, loss=1.0))
    assert [o is not None for o in outs] == [False, False, False, True] * 2


def test_as_monitor_coercions():
    assert health_mod.as_monitor(None) is None
    m = HealthMonitor()
    assert health_mod.as_monitor(m) is m
    assert isinstance(health_mod.as_monitor(True), HealthMonitor)
    assert health_mod.as_monitor({"every": 3}).every == 3
    with pytest.raises(TypeError):
        health_mod.as_monitor(42)


# ---------------------------------------------------------------------------
# the dispatch-bound proof (pytest -m perf discipline)
# ---------------------------------------------------------------------------

def _tiny_module(seed=0):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(seed)
    X = rng.randn(8, 5).astype(np.float32)
    y = np.array([0, 1, 2, 3] * 2, np.float32)
    it = NDArrayIter(X, y, batch_size=4, label_name="softmax_label")
    mod = Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    return mod, next(iter(it))


@pytest.mark.perf
def test_health_dispatch_bound():
    """Health-on adds ZERO extra program executions (the stats are extra
    outputs of the one fused update program) — a sampled step pays one
    batched d2h fetch; an unsampled step pays nothing; health-off is
    byte-for-byte the baseline dispatch sequence."""
    # baseline: health fully off
    os.environ["MXNET_OBS_HEALTH"] = "0"
    try:
        mod, batch = _tiny_module()
        for _ in range(2):
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        with profiler.count_dispatches() as c_off:
            mod.update()
        base_compiled = c_off.total_compiled
        assert c_off.d2h == 0
    finally:
        os.environ.pop("MXNET_OBS_HEALTH")

    # health on, monitor-gated: warm BOTH program variants, then measure
    mod, batch = _tiny_module()
    mon = HealthMonitor(every=2)
    health_mod.activate()
    try:
        for _ in range(4):
            mod.forward(batch, is_train=True)
            mod.backward()
            health_mod.request_stats(mon.will_sample())
            mod.update()
            mon.step(engine=mod._updater._engine)

        # unsampled step: exactly the baseline dispatch sequence
        mod.forward(batch, is_train=True)
        mod.backward()
        health_mod.request_stats(mon.will_sample())
        assert not mon.will_sample()
        with profiler.count_dispatches() as c_unsampled:
            mod.update()
            mon.step(engine=mod._updater._engine)
        assert c_unsampled.total_compiled == base_compiled, \
            c_unsampled.as_dict()
        assert c_unsampled.d2h == 0

        # sampled step: same ONE program (stats variant) + ONE batched d2h
        mod.forward(batch, is_train=True)
        mod.backward()
        health_mod.request_stats(mon.will_sample())
        assert mon.will_sample()
        with profiler.count_dispatches() as c_sampled:
            mod.update()
            rep = mon.step(engine=mod._updater._engine)
        assert rep is not None and rep["ok"]
        assert c_sampled.total_compiled == base_compiled, c_sampled.as_dict()
        assert c_sampled.d2h == 1, c_sampled.as_dict()
    finally:
        health_mod.request_stats(None)
        health_mod.deactivate()


@pytest.mark.perf
def test_health_off_is_zero_cost_noop():
    """With nothing attached, the plane is inert: no stats in the program,
    no flag beyond one check, no registry writes — and turning the obs
    TRACING flag on must NOT drag the in-graph stats along (they are real
    device work; nothing would ever read them without a monitor)."""
    assert not health_mod.enabled()
    assert not health_mod.stats_for_this_step()
    mod, batch = _tiny_module()
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    assert mod._updater._engine.last_health is None
    assert obs.metrics.registry.get("health.samples") is None

    obs.enable()
    assert not health_mod.enabled()  # tracing alone never implies stats
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    assert mod._updater._engine.last_health is None


def test_scaler_masked_overflow_is_not_a_fatal_breach():
    """A found-inf step the scaler already SKIPPED (params untouched) must
    not trip the fatal nonfinite rule — else routine fp16 scale-growth
    overflow would burn the rollback budget a real blowup needs."""
    mon = HealthMonitor(every=1, actions="rollback", skip_streak_threshold=8)
    rep = mon.step(1, engine=_FakeEngine(gnorm=float("inf"),
                                         nonfinite=(9, 0), streak=1))
    assert rep["ok"], rep["breaches"]
    assert rep["action"] == "none"
    # scaler-less: the same sample IS fatal
    mon2 = HealthMonitor(every=1, actions="rollback")
    rep2 = mon2.step(1, engine=_FakeEngine(gnorm=float("inf"),
                                           nonfinite=(9, 0)))
    assert [b["rule"] for b in rep2["breaches"]] == ["nonfinite"]


def test_health_handler_rejects_monitor_false():
    from mxnet_tpu.gluon.contrib.estimator import HealthHandler

    with pytest.raises(ValueError, match="needs a monitor"):
        HealthHandler(monitor=False)


def test_estimator_exception_still_deactivates_health_plane():
    """An exception mid-fit must not leak the plane's activation (the
    fused engine would silently keep emitting stats forever after)."""
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.estimator import Estimator, HealthHandler

    net = nn.HybridSequential()
    net.add(nn.Dense(4))
    net.initialize()
    est = Estimator(net, loss=gloss.SoftmaxCrossEntropyLoss())
    handler = HealthHandler(monitor=HealthMonitor(every=1))

    class _Boom:
        def __iter__(self):
            yield (nd.ones((2, 3)), nd.array([0.0, 1.0]))
            raise RuntimeError("data source died")

    with pytest.raises(RuntimeError, match="data source died"):
        est.fit(train_data=_Boom(), epochs=1, event_handlers=[handler])
    assert health_mod._ACTIVE[0] == 0
    assert not health_mod.enabled()


# ---------------------------------------------------------------------------
# chaos NaN injection + provenance + rollback-target selection
# ---------------------------------------------------------------------------

def test_chaos_nan_env_parse_and_occurrence_counting():
    rules = nan_chaos.parse_env("data@2,4;fc1_weight")
    assert rules[0].tensor == "data" and rules[0].occurrences == {2, 4}
    assert rules[1].tensor == "fc1_weight" and rules[1].occurrences is None
    with pytest.raises(ValueError):
        nan_chaos.parse_env("@3")

    import jax.numpy as jnp

    nan_chaos.configure([nan_chaos.Rule("x", {2})])
    v = jnp.ones((4,))
    out1 = nan_chaos.poison(["x"], [v])     # occurrence 1: clean
    out2 = nan_chaos.poison(["x"], [v])     # occurrence 2: poisoned
    out3 = nan_chaos.poison(["x"], [v])     # occurrence 3: clean again
    assert bool(jnp.all(jnp.isfinite(out1[0])))
    assert not bool(jnp.all(jnp.isfinite(out2[0])))
    assert int(jnp.sum(~jnp.isfinite(out2[0]))) == 1  # exactly one element
    assert bool(jnp.all(jnp.isfinite(out3[0])))


def test_chaos_nan_skips_integer_tensors():
    import jax.numpy as jnp

    nan_chaos.configure([nan_chaos.Rule("idx", None)])
    with pytest.warns(UserWarning, match="non-float"):
        out = nan_chaos.poison(["idx"], [jnp.arange(4)])
    assert bool(jnp.all(out[0] == jnp.arange(4)))


def test_blame_pass_names_first_nonfinite_node(obs_on=None):
    obs.enable()
    mod, batch = _tiny_module()
    nan_chaos.configure([nan_chaos.Rule("data", {1})])
    mod.forward(batch, is_train=True)
    mod.backward()
    res = health_mod.blame_nonfinite(mod._exec)
    assert res["node"] == "fc1" and res["op"] == "FullyConnected"
    assert res["nonfinite_inputs"] == ["data"]
    evs = [e for e in obs.trace.events() if e[1] == "health.nan_provenance"]
    assert len(evs) == 1 and evs[0][6]["node"] == "fc1"


def test_blame_pass_clean_forward_reports_backward():
    mod, batch = _tiny_module()
    mod.forward(batch, is_train=True)
    mod.backward()
    res = health_mod.blame_nonfinite(mod._exec)
    assert res["node"] is None and "backward" in res["detail"]


def test_find_rollback_target_skips_poisoned_checkpoints(tmp_path):
    from mxnet_tpu.checkpoint import CheckpointManager
    from mxnet_tpu.checkpoint.state import TrainingState

    man = CheckpointManager(str(tmp_path), async_write=False)
    good = TrainingState({"arg:w": np.ones((3,), np.float32)},
                         {"format": 1, "epoch": 0, "nbatch": 1,
                          "global_step": 1})
    man.save(good, 1)
    poisoned = TrainingState(
        {"arg:w": np.array([1.0, np.nan, 3.0], np.float32)},
        {"format": 1, "epoch": 0, "nbatch": 2, "global_step": 2})
    man.save(poisoned, 2)
    # CRC-valid but non-finite: the newest snapshot must be REJECTED
    target = health_mod.find_rollback_target(man)
    assert target is not None and target.global_step == 1
    man.close()


# ---------------------------------------------------------------------------
# the flagship: NaN mid-epoch -> breach -> blame -> rollback -> bitwise
# ---------------------------------------------------------------------------

def _flagship_net():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _flagship_run(ckpt_dir, poison_at=None, health=None):
    np.random.seed(7)
    mx.random.seed(7)
    rng = np.random.RandomState(1234)
    X = rng.randn(64, 10).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.float32)
    it = NDArrayIter(X, y, batch_size=8, shuffle=True,
                     label_name="softmax_label")
    mod = Module(_flagship_net(), context=mx.cpu())
    if poison_at is not None:
        nan_chaos.configure([nan_chaos.Rule("data", {poison_at})])
    else:
        nan_chaos.reset()
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            eval_metric="ce", checkpoint=str(ckpt_dir), resume="never",
            checkpoint_batch_period=1, health=health)
    nan_chaos.reset()
    arg, _aux = mod.get_params()
    return {k: v.asnumpy() for k, v in arg.items()}


def test_flagship_nan_breach_blame_rollback_bitwise(tmp_path):
    """Acceptance flagship: a NaN injected mid-epoch produces a tagged
    provenance event naming the first non-finite op, a sentinel breach,
    an auto-rollback, and a resumed segment bitwise-identical to an
    uninjected run — all visible in one chrome trace with loss/grad-norm
    counter tracks."""
    import json

    obs.enable()
    ref = _flagship_run(tmp_path / "ref")
    mon = HealthMonitor(every=1, actions="rollback")
    out = _flagship_run(tmp_path / "chaos", poison_at=5, health=mon)

    assert mon.rollbacks_done == 1
    for k in ref:
        assert np.array_equal(ref[k], out[k]), f"param {k} drifted"

    trace_path = str(tmp_path / "trace.json")
    obs.export(trace_path)
    with open(trace_path) as f:
        doc = json.load(f)  # valid chrome-trace JSON
    names = {e.get("name") for e in doc["traceEvents"]}
    for required in ("chaos.nan", "health.breach", "health.nan_provenance",
                     "health.rollback", "health.loss", "health.grad_norm"):
        assert required in names, f"missing {required} in trace"
    prov = [e for e in doc["traceEvents"]
            if e.get("name") == "health.nan_provenance"]
    assert prov[0]["args"]["node"] == "fc1"

    # ...and tools/trace_report.py tells the same story as a section
    import trace_report

    rep = trace_report.report([trace_path])
    h = rep["health"]
    assert h is not None
    assert any(b["rule"] == "nonfinite" for b in h["breaches"])
    assert h["provenance"][0]["node"] == "fc1"
    assert any(a["what"] == "health.rollback" for a in h["actions"])
    assert {t["name"] for t in h["tracks"]} >= {"health.loss",
                                                "health.grad_norm"}


def test_fit_health_without_checkpoint_warns_not_crashes(tmp_path):
    """A rollback request with no checkpoint manager degrades to a warning
    — the fit completes (on NaN'd params, honestly reported)."""
    mon = HealthMonitor(every=1, actions="rollback")
    np.random.seed(3)
    mx.random.seed(3)
    rng = np.random.RandomState(5)
    X = rng.randn(32, 10).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.float32)
    it = NDArrayIter(X, y, batch_size=8, label_name="softmax_label")
    mod = Module(_flagship_net(), context=mx.cpu())
    nan_chaos.configure([nan_chaos.Rule("data", {2})])
    mod.fit(it, num_epoch=1, optimizer="sgd", eval_metric="ce", health=mon)
    assert mon.last_report is not None
    assert mon.rollbacks_done == 0


# ---------------------------------------------------------------------------
# integration satellites: Trainer, estimator, Monitor adapter
# ---------------------------------------------------------------------------

def test_trainer_attach_skip_streak_breach_through_real_scaler():
    from mxnet_tpu import amp, autograd, nd
    from mxnet_tpu.gluon import Trainer, nn

    net = nn.Dense(4, in_units=3)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.01})
    scaler = amp.LossScaler()
    amp.init_trainer(tr, scaler)
    mon = tr.attach_health_monitor(
        HealthMonitor(every=1, skip_streak_threshold=3))
    x = nd.ones((2, 3))
    try:
        for i in range(5):
            with autograd.record():
                loss = (net(x) ** 2).sum() * np.nan  # every step overflows
            loss.backward()
            tr.step(2)
        rep = mon.last_report
        assert rep is not None
        rules = {b["rule"] for b in rep["breaches"]}
        assert "scaler_skip_streak" in rules
        # a scaler-masked overflow is NOT fatal (update skipped, params
        # untouched) — only the streak breaches
        assert "nonfinite" not in rules
        assert rep["skip_streak"] >= 3
    finally:
        tr.attach_health_monitor(None)


def test_estimator_health_handler_samples_and_stops_on_nonfinite():
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.estimator import Estimator, HealthHandler

    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.Dense(4))
    net.initialize()
    est = Estimator(net, loss=gloss.SoftmaxCrossEntropyLoss())
    handler = HealthHandler(monitor=HealthMonitor(every=2),
                            stop_on_nonfinite=True)
    rng = np.random.RandomState(0)
    batches = [(nd.array(rng.randn(4, 6).astype(np.float32)),
                nd.array(np.array([0, 1, 2, 3], np.float32)))
               for _ in range(6)]
    est.fit(train_data=batches, epochs=1, event_handlers=[handler])
    rep = handler.monitor.last_report
    assert rep is not None and rep["loss"] is not None
    assert rep["grad_norm"] is not None  # engine stats flowed through


def test_monitor_adapter_routes_health_gauges():
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.monitor import Monitor

    obs.enable()
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.Dense(4))
    net.initialize()
    mon = Monitor(interval=1, pattern=".*dense.*")
    mon.install_gluon(net)
    try:
        mon.tic()
        net(mx.nd.ones((2, 6)))
        with profiler.count_dispatches() as c:
            stats = mon.toc()
    finally:
        mon.uninstall_gluon()
    assert len(stats) >= 2
    assert c.d2h == 1  # still ONE batched transfer, via health.batched_fetch
    gauges = [n for n in obs.metrics.registry.names()
              if n.startswith("health.monitor.")]
    assert len(gauges) >= 2


def test_health_metrics_land_in_prometheus_exposition():
    from mxnet_tpu.obs.export import to_prometheus

    obs.enable()
    mon = HealthMonitor(every=1)
    mon.step(1, loss=1.25, engine=_FakeEngine(gnorm=2.0))
    text = to_prometheus(obs.metrics.snapshot())
    assert "health_loss" in text and "health_grad_norm" in text
