"""Runtime telemetry suite (``pytest -m obs`` / ``make obs``).

Covers the obs layer's contracts (docs/OBSERVABILITY.md):

1. span tracer — nesting, cross-thread reentrancy, ring-buffer bounding;
2. the flagship instrumented run — a 2-batch resnet ``Module.fit`` with
   checkpointing plus a parameter-server RPC round produces a VALID
   chrome-trace JSON containing all six step phases, a kvstore RPC
   histogram, and a checkpoint span, and ``tools/trace_report.py`` renders
   it;
3. metrics registry — snapshot stability, exact concurrent counting,
   type-conflict rejection;
4. disabled mode — no-op singleton spans, no retained allocations, the
   dispatch-count fast path unchanged;
5. chaos visibility — an injected RPC drop appears as a tagged event in
   the same timeline;
6. the satellites — fused compile/execute/retrace metrics, prefetch
   queue/stall metrics, Monitor's batched device_get, Speedometer's
   monotonic clock + zero-elapsed guard, checkpoint writer error
   surfacing.
"""
import json
import os
import sys
import threading
import time
import tracemalloc

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import obs, profiler
from mxnet_tpu import symbol as sym
from mxnet_tpu.io import NDArrayIter, PrefetchingIter
from mxnet_tpu.module import Module

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

STEP_PHASES = ("data_wait", "forward", "backward", "update", "metric",
               "checkpoint")


@pytest.fixture(autouse=True)
def _obs_clean():
    """Telemetry off + empty around every test: obs state must never leak
    into (or out of) a test."""
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


@pytest.fixture
def obs_on(_obs_clean):
    obs.enable()
    yield


# ---------------------------------------------------------------------------
# span tracer: nesting, threads, bounding
# ---------------------------------------------------------------------------

def test_span_nesting_records_depth_and_order(obs_on):
    with obs.trace.span("outer", k=1):
        with obs.trace.span("inner"):
            pass
        with obs.trace.span("inner2"):
            pass
    evs = obs.trace.events()
    by_name = {e[1]: e for e in evs}
    assert set(by_name) == {"outer", "inner", "inner2"}
    # record tuple: (ph, name, t0, dur, tid, depth, attrs)
    assert by_name["outer"][5] == 0 and by_name["outer"][6] == {"k": 1}
    assert by_name["inner"][5] == 1 and by_name["inner2"][5] == 1
    # children close before the parent, and nest inside its interval
    assert evs[0][1] == "inner" and evs[-1][1] == "outer"
    o_t0, o_dur = by_name["outer"][2], by_name["outer"][3]
    for child in ("inner", "inner2"):
        c_t0, c_dur = by_name[child][2], by_name[child][3]
        assert o_t0 <= c_t0 and c_t0 + c_dur <= o_t0 + o_dur + 1e-6


def test_span_reentrancy_across_threads(obs_on):
    n_threads = 6
    start = threading.Barrier(n_threads)

    def worker(i):
        start.wait()
        for _ in range(3):
            with obs.trace.span("outer", worker=i):
                with obs.trace.span("inner", worker=i):
                    time.sleep(0.001)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = obs.trace.events()
    assert len(evs) == n_threads * 3 * 2
    # per-thread stacks: every inner is depth 1, every outer depth 0, and
    # depths never bleed across concurrent threads
    for e in evs:
        assert e[5] == (1 if e[1] == "inner" else 0)
    tids = {e[4] for e in evs}
    assert len(tids) == n_threads


def test_ring_buffer_is_bounded():
    from mxnet_tpu.obs.trace import Tracer, _ENABLED  # noqa: F401

    t = Tracer(capacity=16)
    obs.enable()
    for i in range(100):
        with t.span(f"s{i}"):
            pass
    assert len(t.events()) == 16
    assert t.events()[-1][1] == "s99"  # newest win, oldest drop


def test_instant_events_and_jsonl_stream(tmp_path):
    path = str(tmp_path / "events.jsonl")
    obs.enable(jsonl=path)
    with obs.trace.span("phase"):
        obs.trace.event("mark", kind="demo")
    obs.metrics.counter("demo.count").inc(3)
    obs.disable()  # appends the final metrics record
    lines = [json.loads(l) for l in open(path) if l.strip()]
    phs = [l["ph"] for l in lines]
    assert "i" in phs and "X" in phs and phs[-1] == "M"
    assert lines[-1]["metrics"]["counters"]["demo.count"] == 3
    # the instant event streams BEFORE the enclosing span closes
    assert phs.index("i") < phs.index("X")


# ---------------------------------------------------------------------------
# flagship: 2-batch resnet fit + PS RPC + checkpoint, exported and reported
# ---------------------------------------------------------------------------

def _tiny_resnet(num_classes=2):
    """One non-bottleneck residual unit at 8x8 — the smallest thing that is
    honestly a ResNet (conv/BN/relu + identity shortcut)."""
    data = sym.Variable("data")
    body = sym.Convolution(data, num_filter=4, kernel=(3, 3), stride=(1, 1),
                           pad=(1, 1), no_bias=True, name="conv0")
    bn1 = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=0.9,
                        name="bn1")
    act1 = sym.Activation(bn1, act_type="relu", name="relu1")
    conv1 = sym.Convolution(act1, num_filter=4, kernel=(3, 3), stride=(1, 1),
                            pad=(1, 1), no_bias=True, name="conv1")
    body = conv1 + body  # residual shortcut
    pool = sym.Pooling(body, global_pool=True, kernel=(8, 8),
                       pool_type="avg", name="pool1")
    flat = sym.Flatten(pool, name="flatten")
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")


def _ps_round():
    """One init/push/pull round against a live PS so the trace carries real
    kvstore RPC spans + histograms."""
    from mxnet_tpu.kvstore.ps_client import PSClient
    from mxnet_tpu.kvstore.ps_server import PSServer

    srv = PSServer(host="127.0.0.1", port=0, num_workers=1)
    srv.start()
    try:
        cli = PSClient("127.0.0.1", srv.port, timeout=5, retries=3,
                       retry_interval=0.05)
        w = np.ones((4, 3), np.float32)
        cli.init("w", w)
        cli.push("w", np.full((4, 3), 0.5, np.float32))
        out = cli.pull("w")
        np.testing.assert_allclose(out, w + 0.5)
    finally:
        srv.stop()


def test_two_batch_resnet_fit_trace_is_valid_and_phase_complete(
        tmp_path, obs_on):
    rng = np.random.RandomState(7)
    X = rng.randn(8, 3, 8, 8).astype(np.float32)
    y = rng.randint(0, 2, 8).astype(np.float32)
    it = NDArrayIter(X, y, batch_size=4)  # 2 batches/epoch
    mod = Module(_tiny_resnet(), context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            checkpoint=str(tmp_path / "ckpts"))
    _ps_round()

    trace_path = str(tmp_path / "trace.json")
    obs.export(trace_path)
    doc = json.load(open(trace_path))  # valid chrome-trace JSON
    assert isinstance(doc["traceEvents"], list)
    names = [e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"]
    for phase in STEP_PHASES:
        assert phase in names, f"missing step phase {phase!r}"
    # 2 batches → 2 of each per-batch phase
    for phase in ("forward", "backward", "update", "metric"):
        assert names.count(phase) == 2
    assert "checkpoint.write" in names  # the async writer's commit
    assert "kvstore.rpc" in names      # client-side RPC spans
    metrics = doc["otherData"]["metrics"]
    rpc_hists = [n for n in metrics["histograms"]
                 if n.startswith("kvstore.rpc.") and n.endswith("_seconds")]
    assert rpc_hists, "expected at least one kvstore RPC latency histogram"
    srv_hists = [n for n in metrics["histograms"]
                 if n.startswith("kvstore.server.rpc.")]
    assert srv_hists, "expected server-side RPC histograms"
    assert "checkpoint.write_seconds" in metrics["histograms"]
    assert metrics["counters"]["kvstore.bytes_pushed"] > 0
    assert metrics["counters"]["kvstore.bytes_pulled"] > 0

    # trace_report renders the same facts
    import trace_report

    rep = trace_report.report(trace_path)
    phase_names = [r["name"] for r in rep["phases"]]
    assert list(phase_names[:6]) == list(STEP_PHASES)
    import io

    buf = io.StringIO()
    trace_report.render(rep, stream=buf)
    text = buf.getvalue()
    for phase in STEP_PHASES:
        assert phase in text
    assert "kvstore.rpc." in text and "checkpoint.write" in text


def test_trace_report_cli_on_jsonl(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    obs.enable(jsonl=path)
    for phase in STEP_PHASES:
        with obs.trace.span(phase):
            pass
    obs.observe("kvstore.rpc.push_seq_seconds", 0.003)
    obs.disable()

    import trace_report

    trace_report.main([path, "--top", "3"])
    out = capsys.readouterr().out
    for phase in STEP_PHASES:
        assert phase in out
    assert "kvstore.rpc.push_seq_seconds" in out


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_metrics_snapshot_stable_and_isolated():
    reg = obs.metrics.registry
    reg.counter("a.count").inc(5)
    reg.gauge("a.gauge").set(1.25)
    h = reg.histogram("a.hist")
    for v in (0.001, 0.002, 0.004, 1.5):
        h.observe(v)
    s1, s2 = reg.snapshot(), reg.snapshot()
    assert s1 == s2  # no ops between snapshots → identical
    assert s1["counters"]["a.count"] == 5
    assert s1["gauges"]["a.gauge"] == 1.25
    hs = s1["histograms"]["a.hist"]
    assert hs["count"] == 4
    assert hs["min"] == pytest.approx(0.001)
    assert hs["max"] == pytest.approx(1.5)
    assert hs["sum"] == pytest.approx(1.507)
    # snapshots are copies: mutating one must not touch the registry
    s1["counters"]["a.count"] = 999
    assert reg.counter("a.count").value == 5
    # dump() renders both formats without blowing up
    assert "a.hist" in reg.dump("text")
    assert json.loads(reg.dump("json"))["counters"]["a.count"] == 5


def test_metrics_concurrent_increments_are_exact():
    reg = obs.metrics.registry
    c = reg.counter("race.count")
    h = reg.histogram("race.hist")

    def worker():
        for _ in range(1000):
            c.inc()
            h.observe(0.01)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000
    assert h.sum == pytest.approx(80.0)


def test_metric_type_conflict_raises():
    reg = obs.metrics.registry
    reg.counter("typed.metric")
    with pytest.raises(TypeError):
        reg.gauge("typed.metric")
    with pytest.raises(TypeError):
        reg.histogram("typed.metric")


def test_histogram_quantile_estimates():
    h = obs.metrics.registry.histogram("q.hist")
    for _ in range(90):
        h.observe(0.002)
    for _ in range(10):
        h.observe(0.2)
    assert h.quantile(0.5) == pytest.approx(0.0025)  # bucket upper bound
    assert h.quantile(0.99) >= 0.2


# ---------------------------------------------------------------------------
# disabled mode: the zero-cost contract
# ---------------------------------------------------------------------------

def test_disabled_span_is_shared_noop_and_records_nothing():
    assert not obs.enabled()
    s1 = obs.trace.span("forward", epoch=1)
    s2 = obs.trace.span("backward")
    assert s1 is s2  # the shared singleton — no per-call object
    with s1:
        obs.trace.event("never", x=1)
    assert obs.trace.events() == []
    # the self-gating helpers must not even create the metrics
    obs.inc("never.count")
    obs.observe("never.hist", 1.0)
    obs.set_gauge("never.gauge", 1.0)
    assert obs.metrics.registry.get("never.count") is None
    assert obs.metrics.registry.get("never.hist") is None
    assert obs.metrics.registry.get("never.gauge") is None


def test_disabled_hot_path_retains_no_allocations():
    assert not obs.enabled()

    def hot_loop(n):
        for _ in range(n):
            with obs.trace.span("phase"):
                pass
            obs.inc("c")
            obs.observe("h", 0.5)

    hot_loop(100)  # warm caches outside the measurement
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    hot_loop(20000)
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    retained = sum(s.size_diff for s in after.compare_to(before, "filename")
                   if s.size_diff > 0)
    # 20k disabled iterations must retain (essentially) nothing; a real
    # recording of 20k spans would be megabytes
    assert retained < 64 * 1024, f"disabled mode retained {retained} bytes"
    assert obs.trace.events() == []


def test_dispatch_counting_unchanged_when_disabled():
    assert not obs.enabled()
    assert not profiler.counting_dispatches()  # no region, no obs → off
    reg = obs.metrics.registry
    with profiler.count_dispatches() as c:
        a = mx.nd.ones((4, 4))
        b = (a * a + a).asnumpy()  # noqa: F841
    assert c.eager_ops >= 2 and c.d2h == 1
    # the region view IS the registry delta — same numbers, one source
    assert reg.counter("dispatch.eager_ops").value >= c.eager_ops
    assert not profiler.counting_dispatches()


def test_dispatch_counts_accumulate_globally_when_enabled(obs_on):
    assert profiler.counting_dispatches()  # obs enabled → hooks active
    before = obs.metrics.registry.counter("dispatch.eager_ops").value
    a = mx.nd.ones((2, 2))
    _ = a + a
    assert obs.metrics.registry.counter("dispatch.eager_ops").value > before


# ---------------------------------------------------------------------------
# chaos visibility: injected faults are tagged in the same timeline
# ---------------------------------------------------------------------------

def test_injected_rpc_drop_appears_as_tagged_event(obs_on):
    from mxnet_tpu.chaos import rpc as chaos_rpc
    from mxnet_tpu.kvstore.ps_client import PSClient
    from mxnet_tpu.kvstore.ps_server import PSServer

    chaos_rpc.reset()
    srv = PSServer(host="127.0.0.1", port=0, num_workers=1)
    srv.start()
    try:
        cli = PSClient("127.0.0.1", srv.port, timeout=5, retries=5,
                       retry_interval=0.01)
        w = np.zeros((3,), np.float32)
        cli.init("w", w)
        chaos_rpc.configure(
            [chaos_rpc.Rule("push_seq", "drop_reply", {1})])
        cli.push("w", np.ones((3,), np.float32))
        np.testing.assert_allclose(cli.pull("w"), np.ones(3))  # exactly once
    finally:
        chaos_rpc.reset()
        srv.stop()

    drops = [e for e in obs.trace.events()
             if e[0] == "i" and e[1] == "chaos.rpc"]
    assert drops, "injected drop not tagged in the trace"
    attrs = drops[0][6]
    assert attrs["action"] == "drop_reply" and attrs["op"] == "push_seq"
    reg = obs.metrics.registry
    assert reg.counter("chaos.injected").value >= 1
    assert reg.counter("kvstore.rpc.retries").value >= 1
    assert reg.histogram("kvstore.rpc.push_seq_seconds").count >= 1
    # the retry itself is also an event, ordered after the injection
    retries = [e for e in obs.trace.events() if e[1] == "kvstore.rpc.retry"]
    assert retries and retries[0][2] >= drops[0][2]


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------

def test_fused_update_compile_execute_and_retrace_metrics(obs_on):
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import Trainer, nn

    net = nn.Dense(4)
    net.initialize()
    x = mx.nd.ones((2, 3))
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1})

    def step():
        with autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        trainer.step(2)

    step()
    step()
    reg = obs.metrics.registry
    assert reg.counter("update.compile").value == 1
    assert reg.counter("update.retrace").value == 0
    assert reg.histogram("update.compile_seconds").count == 1
    assert reg.histogram("update.execute_seconds").count == 1
    # churning a STATIC hyperparameter forces a recompile → retrace counter
    trainer._optimizer.clip_gradient = 5.0
    step()
    assert reg.counter("update.retrace").value == 1
    assert reg.counter("update.compile").value == 2
    spans = [e for e in obs.trace.events() if e[1] == "update.fused"]
    assert [s[6]["compile"] for s in spans] == [True, False, True]


def test_prefetch_reports_queue_depth_and_stall(obs_on):
    X = np.arange(64, dtype=np.float32).reshape(16, 4)
    y = np.zeros(16, np.float32)
    it = PrefetchingIter(NDArrayIter(X, y, batch_size=4))
    try:
        n = sum(1 for _ in it)
    finally:
        it.close()
    assert n == 4
    reg = obs.metrics.registry
    assert reg.counter("io.prefetch.batches").value == 4
    assert reg.histogram("io.prefetch.stall_seconds").count == 4
    assert reg.get("io.prefetch.queue_depth") is not None


def test_monitor_batches_stat_transfers(obs_on):
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.monitor import Monitor

    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.Dense(4))
    net.initialize()
    mon = Monitor(interval=1, pattern=".*dense.*")
    mon.install_gluon(net)
    try:
        mon.tic()
        net(mx.nd.ones((2, 6)))
        with profiler.count_dispatches() as c:
            stats = mon.toc()
    finally:
        mon.uninstall_gluon()
    assert len(stats) >= 2  # both Dense layers tapped
    for _step, _name, val in stats:
        assert isinstance(val, np.ndarray)
    # ONE batched device_get for all stats (the old code paid one blocking
    # asnumpy per watched tensor)
    assert c.d2h == 1
    # ...and the stats land in the registry as health-plane gauges (the
    # Monitor is an adapter over obs/health.py since the health PR)
    gauges = [n for n in obs.metrics.registry.names()
              if n.startswith("health.monitor.")]
    assert len(gauges) >= 2


def test_speedometer_monotonic_and_zero_elapsed_guard(obs_on):
    from mxnet_tpu.callback import BatchEndParam, Speedometer

    spm = Speedometer(batch_size=2, frequent=1)
    spm(BatchEndParam(epoch=0, nbatch=0, eval_metric=None, locals=None))
    # same clock tick as the init call — the old time.time() version could
    # divide by zero here
    spm(BatchEndParam(epoch=0, nbatch=1, eval_metric=None, locals=None))
    g = obs.metrics.registry.get("training.samples_per_sec")
    assert g is not None and g.value > 0


def test_checkpoint_writer_error_logged_counted_and_reraised(
        tmp_path, monkeypatch, caplog):
    import logging

    from mxnet_tpu.checkpoint import CheckpointError, CheckpointManager
    from mxnet_tpu.checkpoint.state import TrainingState
    from mxnet_tpu.ndarray import serialization as ser

    def boom(*a, **k):
        raise OSError("disk on fire")

    monkeypatch.setattr(ser, "save_nd", boom)
    reg = obs.metrics.registry
    before = reg.counter("checkpoint.write_errors").value
    m = CheckpointManager(str(tmp_path), async_write=True)
    st = TrainingState({"arg:w": np.ones(3, np.float32)}, {"epoch": 0})
    with caplog.at_level(logging.ERROR, logger="mxnet_tpu.checkpoint"):
        m.save(st, 1)
        # the failure surfaces on the NEXT sync point, as CheckpointError
        with pytest.raises(CheckpointError):
            m.flush()
    assert reg.counter("checkpoint.write_errors").value == before + 1
    assert any("write failed" in r.message for r in caplog.records)
    # the error is consumed once surfaced; recovery works
    monkeypatch.undo()
    m.save(st, 2)
    m.close()
    assert m.latest_step() == 2


def test_checkpoint_write_durations_recorded(tmp_path, obs_on):
    from mxnet_tpu.checkpoint import CheckpointManager
    from mxnet_tpu.checkpoint.state import TrainingState

    m = CheckpointManager(str(tmp_path), async_write=False)
    m.save(TrainingState({"arg:w": np.ones(4, np.float32)}, {"epoch": 0}), 1)
    m.close()
    reg = obs.metrics.registry
    for name in ("checkpoint.write_seconds", "checkpoint.array_write_seconds",
                 "checkpoint.fsync_seconds", "checkpoint.commit_seconds"):
        assert reg.histogram(name).count == 1, name
    assert reg.counter("checkpoint.saves").value == 1
    assert any(e[1] == "checkpoint.write" for e in obs.trace.events())
