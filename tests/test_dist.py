"""Multi-process distributed kvstore tests: 3 real worker processes on
localhost through tools/launch.py (reference nightly dist kvstore tests +
dmlc local tracker — SURVEY.md §3.4/§4)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_worker.py")
LAUNCH = os.path.join(REPO, "tools", "launch.py")


def _run_launcher(extra_args, mode, timeout=240, env_extra=None):
    env = dict(os.environ)
    # children get exactly one CPU device each (parent conftest forces 8)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    cmd = [sys.executable, LAUNCH, *extra_args,
           sys.executable, WORKER, mode]
    proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=timeout,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True)
    assert proc.returncode == 0, f"launcher rc={proc.returncode}\n{proc.stdout[-4000:]}"
    return proc.stdout


# This image's jaxlib CPU backend rejects cross-process collectives
# ("Multiprocess computations aren't implemented on the CPU backend"), so
# the jax.distributed dist_sync transport cannot run here at all — an
# environment limitation, not a framework regression (docs/ROBUSTNESS.md
# "Elastic training", carried-failure triage). The SAME known-value worker
# passes over the elastic PS-reduce transport below, which keeps every
# dist_sync semantic covered on this box.
_CPU_COLLECTIVES = pytest.mark.xfail(
    reason="jaxlib CPU backend lacks multiprocess collectives; dist_sync "
    "semantics are covered by the elastic-transport twins below",
    strict=False)


@_CPU_COLLECTIVES
def test_dist_sync_three_workers():
    out = _run_launcher(["-n", "3"], "dist_sync")
    assert out.count("OK") == 3, out[-2000:]


@_CPU_COLLECTIVES
def test_dist_sync_four_workers():
    """n=4 known-value run (VERDICT r3 item 6: dist testing stopped at 3
    processes; the reference nightly runs more — dist_sync_kvstore.py TBV).
    Covers dense sum, row_sparse, 2-bit compression, optimizer-on-store."""
    out = _run_launcher(["-n", "4"], "dist_sync", timeout=360)
    assert out.count("OK") == 4, out[-2000:]


@pytest.mark.elastic
def test_dist_sync_elastic_three_workers():
    """The full dist_sync known-value suite (rank-0-wins init, exact dense
    sums, push merge, 2-bit compressed fused collective, row_sparse,
    optimizer-on-store) over the elastic PS-reduce transport — the
    generation-scoped allreduce must be EXACT, not approximately right."""
    out = _run_launcher(["-n", "3", "-e"], "dist_sync",
                        env_extra={"MXNET_ELASTIC": "1"})
    assert out.count("OK") == 3, out[-2000:]


@pytest.mark.elastic
def test_dist_sync_elastic_four_workers():
    out = _run_launcher(["-n", "4", "-e"], "dist_sync", timeout=360,
                        env_extra={"MXNET_ELASTIC": "1"})
    assert out.count("OK") == 4, out[-2000:]


def test_dist_async_four_workers_native_ps():
    ps_bin = os.path.join(REPO, "native", "build", "mxtpu_ps_server")
    if not os.path.exists(ps_bin):
        pytest.skip("native PS server not built")
    out = _run_launcher(["-n", "4", "-s", "1"], "dist_async", timeout=360)
    assert out.count("OK") == 4, out[-2000:]


def test_dist_async_three_workers_native_ps():
    ps_bin = os.path.join(REPO, "native", "build", "mxtpu_ps_server")
    if not os.path.exists(ps_bin):
        pytest.skip("native PS server not built")
    out = _run_launcher(["-n", "3", "-s", "1"], "dist_async")
    assert out.count("OK") == 3, out[-2000:]


def test_dist_async_python_ps(tmp_path, monkeypatch):
    """Same known-value run against the python twin server."""
    ps_bin = os.path.join(REPO, "native", "build", "mxtpu_ps_server")
    hidden = str(tmp_path / "mxtpu_ps_server")
    if os.path.exists(ps_bin):
        os.rename(ps_bin, hidden)
    try:
        out = _run_launcher(["-n", "2", "-s", "1"], "dist_async")
        assert out.count("OK") == 2, out[-2000:]
    finally:
        if os.path.exists(hidden):
            os.rename(hidden, ps_bin)
