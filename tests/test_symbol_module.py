"""Symbolic frontend + Executor + Module tests (reference test_symbol.py /
test_module.py analogs — SURVEY.md §4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import symbol as sym
from mxnet_tpu.io import DataBatch, NDArrayIter
from mxnet_tpu.module import BucketingModule, Module


def _mlp_symbol(hidden=16, classes=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_symbol_arguments_and_outputs():
    s = _mlp_symbol()
    args = s.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
                    "softmax_label"]
    assert s.list_outputs() == ["softmax_output"]


def test_symbol_infer_shape():
    s = _mlp_symbol(hidden=16, classes=4)
    arg_shapes, out_shapes, aux_shapes = s.infer_shape(data=(8, 10))
    args = s.list_arguments()
    d = dict(zip(args, arg_shapes))
    assert d["fc1_weight"] == (16, 10)
    assert d["fc1_bias"] == (16,)
    assert d["fc2_weight"] == (4, 16)
    assert out_shapes == [(8, 4)]


def test_symbol_json_roundtrip():
    s = _mlp_symbol()
    js = s.tojson()
    s2 = sym.load_json(js)
    assert s2.list_arguments() == s.list_arguments()
    arg_shapes, out_shapes, _ = s2.infer_shape(data=(2, 6))
    assert out_shapes == [(2, 4)]


def test_executor_forward_matches_numpy():
    data = sym.Variable("data")
    w = sym.Variable("w")
    out = sym.FullyConnected(data, w, no_bias=True, num_hidden=3, name="fc")
    exe = out.simple_bind(grad_req="null", data=(2, 5), w=(3, 5))
    x = np.random.RandomState(0).rand(2, 5).astype(np.float32)
    wv = np.random.RandomState(1).rand(3, 5).astype(np.float32)
    (y,) = exe.forward(is_train=False, data=x, w=wv)
    np.testing.assert_allclose(y.asnumpy(), x @ wv.T, rtol=1e-5, atol=1e-6)


def test_executor_backward_gradients():
    data = sym.Variable("data")
    out = sym.FullyConnected(data, num_hidden=1, no_bias=True, name="fc")
    loss = sym.sum(out)
    exe = loss.simple_bind(grad_req="write", data=(4, 3))
    x = np.ones((4, 3), np.float32)
    wv = np.full((1, 3), 2.0, np.float32)
    exe.forward(is_train=True, data=x, fc_weight=wv)
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["fc_weight"].asnumpy(),
                               np.full((1, 3), 4.0), rtol=1e-5)
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               np.full((4, 3), 2.0), rtol=1e-5)


def test_module_fit_mlp():
    """Small real fit reaches high train accuracy (reference
    tests/python/train/test_mlp.py idea)."""
    rng = np.random.RandomState(0)
    n = 256
    x = rng.randn(n, 8).astype(np.float32)
    wtrue = rng.randn(8, 3).astype(np.float32)
    y = np.argmax(x @ wtrue, axis=1).astype(np.float32)
    it = NDArrayIter(x, y, batch_size=32, shuffle=True)

    mod = Module(_mlp_symbol(hidden=32, classes=3), context=mx.cpu())
    mod.fit(it, num_epoch=12,
            optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    score = mod.score(it, "acc")
    assert score[0][1] > 0.9, f"train accuracy too low: {score}"


def test_module_predict_and_checkpoint(tmp_path):
    rng = np.random.RandomState(1)
    x = rng.randn(32, 6).astype(np.float32)
    y = rng.randint(0, 3, 32).astype(np.float32)
    it = NDArrayIter(x, y, batch_size=8)
    mod = Module(_mlp_symbol(hidden=8, classes=3))
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params()
    preds = mod.predict(it)
    assert preds.shape == (32, 3)

    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 1)
    mod2 = Module.load(prefix, 1)
    mod2.bind(it.provide_data, it.provide_label, for_training=False)
    mod2.init_params()
    preds2 = mod2.predict(it)
    np.testing.assert_allclose(preds.asnumpy(), preds2.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_batchnorm_symbolic_aux_update():
    data = sym.Variable("data")
    net = sym.BatchNorm(data, name="bn", fix_gamma=False, momentum=0.5)
    exe = net.simple_bind(grad_req="null", data=(4, 3))
    assert set(exe.aux_dict) == {"bn_moving_mean", "bn_moving_var"}
    x = np.random.RandomState(0).rand(4, 3).astype(np.float32) * 10
    exe.forward(is_train=True, data=x, bn_gamma=np.ones(3, np.float32),
                bn_beta=np.zeros(3, np.float32))
    mm = exe.aux_dict["bn_moving_mean"].asnumpy()
    expected = 0.5 * np.zeros(3) + 0.5 * x.mean(axis=0)
    np.testing.assert_allclose(mm, expected, rtol=1e-4, atol=1e-5)


def test_bucketing_module_shares_params():
    def sym_gen(seq_len):
        data = sym.Variable("data")
        net = sym.FullyConnected(data, num_hidden=4, name="fc",
                                 flatten=False)
        net = sym.mean(net, axis=1)
        net = sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = BucketingModule(sym_gen, default_bucket_key=10)
    mod.bind([("data", (2, 10, 5))], [("softmax_label", (2,))])
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})

    for key, t in ((10, 10), (6, 6), (10, 10)):
        batch = DataBatch([nd.ones((2, t, 5))], [nd.zeros((2,))],
                          bucket_key=key,
                          provide_data=[("data", (2, t, 5))],
                          provide_label=[("softmax_label", (2,))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    # both buckets must share the same parameter storage
    m10 = mod._buckets[10]._exec.arg_dict["fc_weight"]
    m6 = mod._buckets[6]._exec.arg_dict["fc_weight"]
    assert m10 is m6


def test_symbol_arithmetic_and_eval():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = (a + b) * 2.0 - 1.0
    exe = c.simple_bind(grad_req="null", a=(2, 2), b=(2, 2))
    (out,) = exe.forward(a=np.ones((2, 2), np.float32),
                         b=np.ones((2, 2), np.float32))
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 3.0))


def test_multi_output_indexing():
    data = sym.Variable("data")
    s = sym.SliceChannel(data, num_outputs=3, axis=1, name="split")
    assert len(s.list_outputs()) == 3
    first = s[0]
    exe = first.simple_bind(grad_req="null", data=(2, 6))
    (out,) = exe.forward(data=np.arange(12, dtype=np.float32).reshape(2, 6))
    assert out.shape == (2, 2)
