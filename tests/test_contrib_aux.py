"""Contrib ops (multibox/NMS/roi_align) + aux modules (profiler, runtime,
amp, image) — reference test_contrib_*.py analogs."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_multibox_prior():
    x = nd.ones((1, 3, 4, 4))
    anchors = nd.contrib.MultiBoxPrior(x, sizes=(0.5, 0.25), ratios=(1, 2))
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    a = anchors.asnumpy()[0]
    # centers inside [0,1]; first anchor centered at (0.125, 0.125)
    cx = (a[0, 0] + a[0, 2]) / 2
    cy = (a[0, 1] + a[0, 3]) / 2
    np.testing.assert_allclose([cx, cy], [0.125, 0.125], atol=1e-6)
    np.testing.assert_allclose(a[0, 2] - a[0, 0], 0.5, atol=1e-6)


def test_box_nms_suppresses_overlaps():
    # rows: [id, score, l, t, r, b]
    boxes = np.array([[0, 0.9, 0.0, 0.0, 0.5, 0.5],
                      [0, 0.8, 0.05, 0.05, 0.55, 0.55],   # overlaps first
                      [0, 0.7, 0.6, 0.6, 0.9, 0.9],       # separate
                      [0, 0.0, 0.0, 0.0, 0.1, 0.1]],      # below valid_thresh
                     np.float32)
    out = nd.contrib.box_nms(nd.array(boxes[None]), overlap_thresh=0.5,
                             valid_thresh=0.01).asnumpy()[0]
    scores = out[:, 1]
    kept = scores[scores > 0]
    assert len(kept) == 2
    np.testing.assert_allclose(sorted(kept, reverse=True), [0.9, 0.7], atol=1e-6)


def test_box_nms_per_class():
    boxes = np.array([[0, 0.9, 0.0, 0.0, 0.5, 0.5],
                      [1, 0.8, 0.0, 0.0, 0.5, 0.5]], np.float32)  # same box, diff class
    out = nd.contrib.box_nms(nd.array(boxes[None]), overlap_thresh=0.5,
                             id_index=0, force_suppress=False).asnumpy()[0]
    assert (out[:, 1] > 0).sum() == 2  # both kept per-class
    out2 = nd.contrib.box_nms(nd.array(boxes[None]), overlap_thresh=0.5,
                              id_index=0, force_suppress=True).asnumpy()[0]
    assert (out2[:, 1] > 0).sum() == 1


def test_multibox_target_matching():
    anchors = np.array([[0.0, 0.0, 0.5, 0.5],
                        [0.5, 0.5, 1.0, 1.0],
                        [0.0, 0.5, 0.5, 1.0]], np.float32)
    # one gt box matching anchor 0 exactly
    label = np.array([[[1.0, 0.0, 0.0, 0.5, 0.5],
                       [-1, 0, 0, 0, 0]]], np.float32)
    cls_pred = np.zeros((1, 3, 3), np.float32)
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        nd.array(anchors[None]), nd.array(label), nd.array(cls_pred))
    cls = cls_t.asnumpy()[0]
    assert cls[0] == 2.0  # class 1 + 1 (0 is background)
    assert cls[1] == 0.0
    m = loc_m.asnumpy()[0].reshape(3, 4)
    assert m[0].all() and not m[1].any()
    # exact match -> zero offsets
    np.testing.assert_allclose(loc_t.asnumpy()[0][:4], 0.0, atol=1e-5)


def test_multibox_detection_decode():
    anchors = np.array([[0.1, 0.1, 0.3, 0.3],
                        [0.6, 0.6, 0.9, 0.9]], np.float32)
    cls_prob = np.array([[[0.1, 0.8], [0.9, 0.2]]], np.float32)  # (1,C=2,N=2)
    loc_pred = np.zeros((1, 8), np.float32)  # zero offsets -> anchors
    out = nd.contrib.MultiBoxDetection(nd.array(cls_prob), nd.array(loc_pred),
                                       nd.array(anchors[None]))
    o = out.asnumpy()[0]
    valid = o[o[:, 0] >= 0]
    assert valid.shape[0] == 2  # both pass the 0.01 threshold, no overlap
    best = valid[np.argmax(valid[:, 1])]
    np.testing.assert_allclose(best[1], 0.9, atol=1e-5)  # class-1 prob of anchor 0
    np.testing.assert_allclose(best[2:], [0.1, 0.1, 0.3, 0.3], atol=1e-5)


def test_roi_align_shapes_and_values():
    data = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)
    out = nd.contrib.ROIAlign(nd.array(data), nd.array(rois),
                              pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    v = out.asnumpy()[0, 0]
    assert v[0, 0] < v[1, 1]  # increasing values preserved


def test_boolean_mask_compacts():
    data = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = nd.array(np.array([1, 0, 1, 0], np.float32))
    out = nd.contrib.boolean_mask(data, idx).asnumpy()
    np.testing.assert_allclose(out[0], [0, 1, 2])
    np.testing.assert_allclose(out[1], [6, 7, 8])
    np.testing.assert_allclose(out[2:], 0.0)


def test_runtime_features():
    feats = mx.runtime.feature_list()
    d = {f.name: f.enabled for f in feats}
    assert d["XLA"] and d["CPU"]
    assert not d["CUDA"]
    assert mx.runtime.Features().is_enabled("PJIT")


def test_amp_convert_and_loss_scaler():
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.BatchNorm(in_channels=4))
    net.initialize()
    mx.amp.init()
    mx.amp.convert_hybrid_block(net)
    dtypes = {p.name: p.data().dtype for p in net.collect_params().values()}
    assert any(str(d) == "bfloat16" for d in dtypes.values())
    # BN stats stay fp32
    for name, d in dtypes.items():
        if "running" in name or "gamma" in name or "beta" in name:
            assert str(d) == "float32"
    s = mx.amp.LossScaler()
    s.update_scale(skip=True)
    s.update_scale(skip=False)
    assert s.loss_scale > 0


def test_image_api(tmp_path):
    img = np.random.RandomState(0).randint(0, 255, (20, 30, 3)).astype(np.uint8)
    from PIL import Image

    p = str(tmp_path / "t.png")
    Image.fromarray(img).save(p)
    loaded = mx.image.imread(p)
    assert loaded.shape == (20, 30, 3)
    r = mx.image.imresize(loaded, 15, 10)
    assert r.shape == (10, 15, 3)
    c, _ = mx.image.center_crop(loaded, (10, 10))
    assert c.shape == (10, 10, 3)
    augs = mx.image.CreateAugmenter((3, 8, 8), rand_mirror=True, mean=True, std=True)
    out = loaded
    for a in augs:
        out = a(out)
    assert out.shape[0] == 8 or out.shape == (8, 8, 3)


def test_profiler_api(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "prof"))
    mx.profiler.set_state("run")
    (nd.ones((4, 4)) * 2).wait_to_read()
    mx.profiler.set_state("stop")
    d = mx.profiler.dump()
    import os

    assert d and os.path.isdir(d)


def test_runtime_env_registry():
    """Systematic MXNET_*/DMLC_* env surface (SURVEY §5.6; r2 partial)."""
    evs = mx.runtime.env_list()
    names = {e.name for e in evs}
    # every env var the code reads must be declared in the registry
    for expected in ("MXNET_SEED", "MXNET_ENGINE_TYPE", "MX_SYNC",
                     "MXNET_MATMUL_PRECISION", "MXNET_ATTENTION_IMPL",
                     "DMLC_PS_ROOT_URI", "DMLC_NUM_WORKER", "MXNET_PS_ADDR"):
        assert expected in names, expected
    for e in evs:
        assert e.description
    assert "RNG seed" in mx.runtime.env_doc("MXNET_SEED")
