"""Round-3 API-surface closure: autograd.Function, SymbolBlock(+imports),
mx.viz, mx.engine, mx.attribute, mx.name, FeedForward, ProgressBar
(reference python/mxnet package surface — SURVEY.md §2.3)."""
import io
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_autograd_function_custom_vjp():
    class Double(mx.autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * 2

        def backward(self, dy):
            return dy * 2

    x = nd.array(np.array([1.0, 2.0], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = Double()(x)
        z = (y * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [8.0, 16.0])


def test_autograd_function_multi_io():
    class AddMul(mx.autograd.Function):
        def forward(self, a, b):
            return a + b, a * b

        def backward(self, ds, dp):
            # d(a+b)=ds ; d(a*b): need saved a,b — use saved_tensors
            a, b = self.saved_tensors
            return ds + dp * b, ds + dp * a

        def __call__(self, a, b):
            self.save_for_backward(a, b)
            return super().__call__(a, b)

    a = nd.array(np.array([2.0], np.float32))
    b = nd.array(np.array([3.0], np.float32))
    a.attach_grad()
    b.attach_grad()
    with mx.autograd.record():
        s, p = AddMul()(a, b)
        out = s + 2 * p
    out.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [1 + 2 * 3.0])
    np.testing.assert_allclose(b.grad.asnumpy(), [1 + 2 * 2.0])


def test_symbolblock_imports_roundtrip(tmp_path):
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("fc_weight")
    b = mx.sym.Variable("fc_bias")
    out = mx.sym.FullyConnected(data, w, b, num_hidden=4, name="fc")
    out = mx.sym.Activation(out, act_type="relu", name="act")
    rng = np.random.RandomState(0)
    arg = {"fc_weight": nd.array(rng.rand(4, 3).astype(np.float32)),
           "fc_bias": nd.array(np.zeros(4, np.float32))}
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 3, out, arg, {})

    blk = mx.gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                       prefix + "-0003.params")
    x = nd.array(rng.rand(2, 3).astype(np.float32))
    ref = np.maximum(x.asnumpy() @ arg["fc_weight"].asnumpy().T, 0)
    np.testing.assert_allclose(blk(x).asnumpy(), ref, rtol=1e-5)
    blk.hybridize()
    np.testing.assert_allclose(blk(x).asnumpy(), ref, rtol=1e-5)
    # trainable: params registered
    assert set(blk._reg_params) == {"fc_weight", "fc_bias"}
    # a params file missing one graph parameter must be rejected
    mx.model.save_checkpoint(str(tmp_path / "bad"), 0, out,
                             {"fc_weight": arg["fc_weight"]}, {})
    with pytest.raises(KeyError):
        mx.gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                     str(tmp_path / "bad-0000.params"))


def test_viz_print_summary(capsys):
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, mx.sym.Variable("fc1_weight"),
                                mx.sym.Variable("fc1_bias"), num_hidden=8,
                                name="fc1")
    s = mx.viz.print_summary(out, shape={"data": (1, 16)})
    assert "fc1" in s and "Total params" in s
    assert "136" in s  # 16*8 + 8


def test_engine_bulk_scope():
    from mxnet_tpu.ndarray import ndarray as nd_mod

    prev = nd_mod._MX_SYNC
    nd_mod._MX_SYNC = True
    try:
        with mx.engine.bulk(16):
            assert nd_mod._MX_SYNC is False
            x = nd.ones((2,)) + 1
        assert nd_mod._MX_SYNC is True
        np.testing.assert_allclose(x.asnumpy(), [2, 2])
    finally:
        nd_mod._MX_SYNC = prev
    assert mx.engine.set_bulk_size(10) >= 0


def test_attribute_and_name_scopes():
    with mx.attribute.AttrScope(ctx_group="dev1", lr_mult="2"):
        assert mx.attribute.current()["ctx_group"] == "dev1"
        with mx.attribute.AttrScope(lr_mult="3"):
            merged = mx.attribute.current()
            assert merged == {"ctx_group": "dev1", "lr_mult": "3"}
    assert mx.attribute.current() == {}
    with pytest.raises(ValueError):
        mx.attribute.AttrScope(bad=1)

    nm = mx.name.NameManager()
    assert nm.get(None, "conv") == "conv0"
    assert nm.get(None, "conv") == "conv1"
    assert nm.get("explicit", "conv") == "explicit"
    with mx.name.Prefix("net_") as p:
        assert p.get(None, "fc") == "net_fc0"


def test_feedforward_legacy_api(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.rand(64, 8).astype(np.float32)
    yv = (x.sum(1) > 4).astype(np.float32)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    from mxnet_tpu.io import NDArrayIter

    it = NDArrayIter(x, yv, batch_size=16, label_name="softmax_label")
    ff = mx.model.FeedForward(net, num_epoch=2, learning_rate=0.5)
    ff.fit(it)
    assert ff.arg_params and "fc_weight" in ff.arg_params
    preds = ff.predict(NDArrayIter(x, yv, batch_size=16,
                                   label_name="softmax_label"))
    assert preds.shape[0] == 64
    prefix = str(tmp_path / "ff")
    ff.save(prefix, 1)
    again = mx.model.FeedForward.load(prefix, 1)
    np.testing.assert_allclose(
        again.arg_params["fc_weight"].asnumpy(),
        ff.arg_params["fc_weight"].asnumpy())


def test_progress_bar():
    import sys

    pb = mx.callback.ProgressBar(total=4, length=8)

    class P:
        nbatch = 2

    saved = sys.stdout
    sys.stdout = io.StringIO()
    try:
        pb(P())
        out = sys.stdout.getvalue()
    finally:
        sys.stdout = saved
    assert "2/4" in out


def test_list_gpus_tpus():
    assert mx.test_utils.list_gpus() == []
    assert isinstance(mx.test_utils.list_tpus(), list)


def test_symbolblock_eval_mode_and_training():
    """r3 review: imported graphs must respect train/predict mode (Dropout
    off, BN stats frozen at inference) and be trainable eagerly."""
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, mx.sym.Variable("w"), num_hidden=4,
                                no_bias=True, name="fc")
    out = mx.sym.Dropout(out, p=0.5, name="drop")
    rng = np.random.RandomState(0)
    w = nd.array(rng.rand(4, 3).astype(np.float32))
    blk = mx.gluon.SymbolBlock(out, [mx.sym.Variable("data")])
    blk._reg_params["w"].shape = (4, 3)
    blk._reg_params["w"].initialize()
    blk._reg_params["w"].set_data(w)
    x = nd.array(rng.rand(2, 3).astype(np.float32))
    # inference: dropout must be identity (deterministic)
    y1 = blk(x).asnumpy()
    y2 = blk(x).asnumpy()
    np.testing.assert_allclose(y1, y2)
    np.testing.assert_allclose(y1, x.asnumpy() @ w.asnumpy().T, rtol=1e-5)
    # eager training: gradients flow to the imported parameter
    p = blk._reg_params["w"]
    p.grad_req = "write"
    p.data().attach_grad()
    with mx.autograd.record():
        loss = (blk(x) ** 2).sum()
    loss.backward()
    g = p.data().grad.asnumpy()
    assert np.abs(g).sum() > 0


def test_attr_scope_reaches_symbols():
    with mx.attribute.AttrScope(ctx_group="dev1", lr_mult="2"):
        s = mx.sym.FullyConnected(mx.sym.Variable("d"), num_hidden=2,
                                  name="fca")
    assert s.attr("ctx_group") == "dev1"
    assert s.attr("lr_mult") == "2"
    # scope attrs must NOT leak into op kwargs at execution
    exe = s.simple_bind(d=(1, 3))
    outs = exe.forward(d=nd.ones((1, 3)))


def test_name_scope_reaches_symbols():
    with mx.name.Prefix("net_"):
        s = mx.sym.Activation(mx.sym.Variable("x"), act_type="relu")
    assert s.name.startswith("net_")


def test_new_optimizers_converge():
    """AdaMax / Nadam / SGLD / DCASGD minimize a quadratic."""
    rng = np.random.RandomState(0)
    target = rng.rand(6).astype(np.float32)
    mx.random.seed(0)
    for name, lr, tol in (("adamax", 0.1, 0.25), ("nadam", 0.1, 0.25),
                          ("dcasgd", 0.1, 0.25)):
        opt = mx.optimizer.create(name, learning_rate=lr)
        w = nd.array(np.zeros(6, np.float32))
        state = opt.create_state(0, w)
        for _ in range(300):
            g = nd.array(2 * (w.asnumpy() - target))
            opt.update(0, w, g, state)
        err = np.abs(w.asnumpy() - target).max()
        assert err < tol, (name, err)
    # SGLD samples the posterior (iterates have O(1) variance by design) —
    # the TIME-AVERAGE of the chain must concentrate on the optimum
    opt = mx.optimizer.create("sgld", learning_rate=0.05)
    w = nd.array(np.zeros(6, np.float32))
    samples = []
    for i in range(1200):
        g = nd.array(2 * (w.asnumpy() - target))
        opt.update(0, w, g, None)
        if i >= 200:
            samples.append(w.asnumpy().copy())
    err = np.abs(np.mean(samples, axis=0) - target).max()
    assert err < 0.3, ("sgld time-average", err)
    # updater state roundtrip with the new optimizers
    upd = mx.optimizer.Updater(mx.optimizer.create("adamax"))
    w = nd.array(np.ones(3, np.float32))
    upd(0, nd.array(np.ones(3, np.float32)), w)
    blob = upd.get_states()
    upd2 = mx.optimizer.Updater(mx.optimizer.create("adamax"))
    upd2.set_states(blob)


def test_subgraph_fold_bn_pass():
    """Subgraph/pass API (reference subgraph_property analog): folding
    inference BatchNorm into Convolution preserves outputs exactly."""
    rng = np.random.RandomState(0)
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1),
                              no_bias=True, name="conv0")
    bn = mx.sym.BatchNorm(conv, fix_gamma=False, eps=1e-3, name="bn0")
    out = mx.sym.Activation(bn, act_type="relu", name="relu0")

    args = {"conv0_weight": nd.array(rng.rand(4, 3, 3, 3).astype(np.float32)),
            "bn0_gamma": nd.array(rng.rand(4).astype(np.float32) + 0.5),
            "bn0_beta": nd.array(rng.rand(4).astype(np.float32))}
    aux = {"bn0_moving_mean": nd.array(rng.rand(4).astype(np.float32)),
           "bn0_moving_var": nd.array(rng.rand(4).astype(np.float32) + 0.5)}
    x = nd.array(rng.rand(2, 3, 8, 8).astype(np.float32))

    exe = out.bind(args={**args, "data": x}, aux_states=aux)
    ref = exe.forward(is_train=False)[0].asnumpy()

    folded = out.optimize_for("fold_bn", args, aux)
    new_args, new_aux = folded._optimized_args, folded._optimized_aux
    assert folded._folded_bn == ["bn0"]
    assert "bn0_gamma" not in new_args and not new_aux
    assert "conv0_bias" in new_args
    exe2 = folded.bind(args={**new_args, "data": x}, aux_states=new_aux)
    got = exe2.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    # backend aliases route to the standard rewrite set
    assert "fold_bn" in mx.subgraph.list_passes()
    folded2 = out.optimize_for("MKLDNN", args, aux)
    assert folded2._folded_bn == ["bn0"]


def test_symbol_contrib_image_random_namespaces():
    """mx.sym.contrib / .image / .random mirror the nd namespaces
    (reference symbol/contrib.py etc.; SSD symbol code needs contrib)."""
    data = mx.sym.Variable("data")
    anchors = mx.sym.contrib.MultiBoxPrior(data, sizes=(0.5,), ratios=(1.0,))
    x = nd.array(np.random.RandomState(0).rand(1, 3, 4, 4).astype(np.float32))
    out = anchors.eval(data=x)[0]
    assert out.shape[-1] == 4
    flipped_sym = mx.sym.image.flip_left_right(mx.sym.Variable("img"))
    img = nd.array(np.arange(12, dtype=np.uint8).reshape(2, 2, 3))
    got = flipped_sym.eval(img=img)[0]
    np.testing.assert_array_equal(got.asnumpy(), img.asnumpy()[:, ::-1])
    u = mx.sym.random.uniform(low=0.0, high=1.0, shape=(8,))
    vals = u.eval()[0]
    assert vals.shape == (8,)
    assert 0.0 <= float(vals.asnumpy().min())


def test_nd_and_sym_linalg_namespaces():
    """Reference API form: nd.linalg.gemm2 / sym.linalg.potrf resolve to
    the _linalg_* registrations (python/mxnet/ndarray/linalg.py — TBV)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    a = nd.array(np.array([[4.0, 1.0], [1.0, 3.0]], np.float32))
    out = nd.linalg.gemm2(a, a, transpose_b=True)
    np.testing.assert_allclose(out.asnumpy(), a.asnumpy() @ a.asnumpy().T,
                               rtol=1e-6)
    L = nd.linalg.potrf(a)
    np.testing.assert_allclose(L.asnumpy() @ L.asnumpy().T, a.asnumpy(),
                               rtol=1e-5)
    s = mx.sym.Variable("x")
    g = mx.sym.linalg.syrk(s)
    assert g.list_arguments() == ["x"]


def test_linalg_family_completion():
    import numpy as np

    from mxnet_tpu import nd

    rng = np.random.RandomState(0)
    m = rng.rand(3, 3).astype(np.float32)
    spd = m @ m.T + 3 * np.eye(3, dtype=np.float32)
    L = nd.linalg.potrf(nd.array(spd))
    inv = nd.linalg.potri(L)
    np.testing.assert_allclose(inv.asnumpy() @ spd, np.eye(3), atol=1e-4)
    sld = nd.linalg.sumlogdiag(L)
    _, logdet = np.linalg.slogdet(spd)
    np.testing.assert_allclose(2 * float(sld.asnumpy()), logdet, rtol=1e-5)
    a = nd.array(rng.rand(2, 4).astype(np.float32))
    q, lo = nd.linalg.gelqf(a)
    np.testing.assert_allclose((lo.asnumpy() @ q.asnumpy()), a.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(q.asnumpy() @ q.asnumpy().T, np.eye(2),
                               atol=1e-5)
    u, w = nd.linalg.syevd(nd.array(spd))
    rec = u.asnumpy().T @ np.diag(w.asnumpy()) @ u.asnumpy()
    np.testing.assert_allclose(rec, spd, rtol=1e-4, atol=1e-4)


def test_registry_module():
    import mxnet_tpu as mx

    class Base:
        pass

    reg = mx.registry.get_register_func(Base, "thing")
    alias = mx.registry.get_alias_func(Base, "thing")
    create = mx.registry.get_create_func(Base, "thing")

    @alias("a1", "a2")
    class Foo(Base):
        def __init__(self, x=1):
            self.x = x

    reg(Foo)
    assert isinstance(create("foo"), Foo)
    assert isinstance(create("a2"), Foo)
    assert create("foo, x=3").x == 3
    inst = Foo()
    assert create(inst) is inst
    import pytest as _pt
    with _pt.raises(ValueError):
        create("nope")

    class NotSub:
        pass
    with _pt.raises(TypeError):
        reg(NotSub)


def test_fused_cell_bidirectional_unroll():
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.ops.rnn import rnn_param_size

    rng = np.random.RandomState(9)
    T, N, C, H = 3, 2, 4, 3
    cell = mx.rnn.FusedRNNCell(H, num_layers=1, mode="gru",
                               bidirectional=True, prefix="bf_")
    data = mx.sym.Variable("data")
    out, _ = cell.unroll(T, data, layout="NTC")
    n_p = rnn_param_size("gru", C, H, bidirectional=True)
    res = out.eval(data=nd.array(rng.randn(N, T, C).astype(np.float32)),
                   bf_parameters=nd.array(
                       rng.randn(n_p).astype(np.float32) * 0.2))
    r0 = (res[0] if isinstance(res, (list, tuple)) else res)
    assert r0.shape == (N, T, 2 * H)
    assert np.isfinite(r0.asnumpy()).all()


def test_optimizer_family_exports_and_lars():
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    for name in ["AdaMax", "Adamax", "Nadam", "SGLD", "DCASGD", "LARS"]:
        assert hasattr(mx.optimizer, name), name
    opt = mx.optimizer.create("lars", learning_rate=0.1, momentum=0.9)
    w = nd.array(np.ones((4, 3), np.float32))
    g = nd.array(np.full((4, 3), 0.1, np.float32))
    st = opt.create_state(0, w)
    before = w.asnumpy().copy()
    opt.update(0, w, g, st)
    assert not np.allclose(w.asnumpy(), before)
    # trust ratio keeps the step finite and small relative to the weight
    assert np.abs(w.asnumpy() - before).max() < 0.1


def test_initializer_load_and_initdesc():
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    saved = {"arg:w": nd.array(np.full((2, 2), 7.0, np.float32))}
    init = mx.init.Load(saved, default_init=mx.init.Zero())
    arr = nd.array(np.zeros((2, 2), np.float32))
    init("w", arr)
    np.testing.assert_allclose(arr.asnumpy(), 7.0)
    other = nd.array(np.ones((3,), np.float32))
    init("missing_weight", other)   # falls back to Zero
    np.testing.assert_allclose(other.asnumpy(), 0.0)
    import pytest as _pt
    with _pt.raises(ValueError, match="shape mismatch"):
        init("w", nd.array(np.zeros((3, 3), np.float32)))

    d = mx.init.InitDesc("fc_weight", attrs={"__init__": "zeros"})
    assert d == "fc_weight" and d.attrs["__init__"] == "zeros"
