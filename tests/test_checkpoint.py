"""Crash-safe checkpointing (mxnet_tpu/checkpoint/ — docs/ROBUSTNESS.md):
atomic commit protocol, CRC validation and corrupt-fallback, full
training-state capture/restore, and bitwise split-vs-straight training
through Module.fit(checkpoint=..., resume="auto")."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.checkpoint import CheckpointError, CheckpointManager
from mxnet_tpu.checkpoint.atomic import atomic_write_bytes, crc32_bytes
from mxnet_tpu.checkpoint.state import (TrainingState, capture_training_state,
                                        restore_optimizer, restore_rng)
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import Module
from mxnet_tpu.ndarray import serialization as ser


# ---------------------------------------------------------------------------
# serialization: atomic save + CRC footer (satellite)
# ---------------------------------------------------------------------------

def test_save_nd_crc_roundtrip(tmp_path):
    path = str(tmp_path / "a.params")
    arrs = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b": np.ones(3, np.float16)}
    ser.save_nd(path, list(arrs.values()), list(arrs.keys()))
    out = ser.load_nd(path)
    for k, v in arrs.items():
        np.testing.assert_array_equal(out[k], v)


def test_load_nd_rejects_bit_flip(tmp_path):
    from mxnet_tpu.chaos.proc import corrupt_file

    path = str(tmp_path / "a.params")
    ser.save_nd(path, [np.arange(8, dtype=np.float32)], ["w"])
    corrupt_file(path, offset=60)  # inside the raw data block
    with pytest.raises(ValueError, match="CRC mismatch"):
        ser.load_nd(path)


def test_load_nd_rejects_truncation(tmp_path):
    path = str(tmp_path / "a.params")
    ser.save_nd(path, [np.arange(8, dtype=np.float32)], ["w"])
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(data[:-5])  # torn write: mid-footer
    with pytest.raises(ValueError):
        ser.load_nd(path)


def test_load_nd_accepts_legacy_no_footer(tmp_path):
    path = str(tmp_path / "a.params")
    arr = np.arange(8, dtype=np.float32)
    ser.save_nd(path, [arr], ["w"], crc=False)  # upstream byte layout
    np.testing.assert_array_equal(ser.load_nd(path)["w"], arr)


def test_atomic_write_replaces_not_appends(tmp_path):
    path = str(tmp_path / "f.bin")
    atomic_write_bytes(path, b"a" * 100)
    atomic_write_bytes(path, b"b" * 3)
    with open(path, "rb") as f:
        assert f.read() == b"bbb"
    assert [e for e in os.listdir(tmp_path) if ".tmp-" in e] == []


# ---------------------------------------------------------------------------
# CheckpointManager: commit, validate, GC, fallback
# ---------------------------------------------------------------------------

def _state(step, seed=0):
    rng = np.random.RandomState(seed + step)
    return TrainingState(
        {"arg:w": rng.randn(4, 3).astype(np.float32),
         "arg:b": rng.randn(3).astype(np.float32)},
        {"format": 1, "global_step": step, "epoch": 0, "nbatch": step})


def test_manager_save_load_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=0, async_write=False)
    st = _state(5)
    mgr.save(st, 5)
    out = mgr.load(5)
    assert out.global_step == 5
    np.testing.assert_array_equal(out.arrays["arg:w"], st.arrays["arg:w"])
    assert out.arg_params().keys() == {"w", "b"}


def test_manager_keep_last_gc(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=2, async_write=False)
    for s in (1, 2, 3, 4):
        mgr.save(_state(s), s)
    assert mgr.list_steps() == [3, 4]


def test_manager_async_writer_flush(tmp_path):
    with CheckpointManager(str(tmp_path), keep_last=0) as mgr:
        for s in (1, 2, 3):
            mgr.save(_state(s), s)
        mgr.flush()
        assert mgr.list_steps() == [1, 2, 3]
        assert mgr.load_latest().global_step == 3


def test_manager_sweeps_stale_staging(tmp_path):
    stale = tmp_path / ".ckpt-00000009.tmp-12345"
    stale.mkdir()
    (stale / "arrays.bin").write_bytes(b"partial")
    mgr = CheckpointManager(str(tmp_path), async_write=False)
    assert not stale.exists()
    assert mgr.list_steps() == []


@pytest.mark.chaos
def test_manager_corrupt_newest_falls_back(tmp_path):
    """Acceptance: a bit-flipped newest checkpoint is detected via CRC and
    skipped in favor of the previous valid one."""
    from mxnet_tpu.chaos.proc import corrupt_file

    mgr = CheckpointManager(str(tmp_path), keep_last=0, async_write=False)
    mgr.save(_state(1), 1)
    mgr.save(_state(2), 2)
    corrupt_file(str(tmp_path / "ckpt-00000002" / "arrays.bin"), offset=60)
    with pytest.raises(CheckpointError):
        mgr.validate(2)
    st = mgr.load_latest()
    assert st is not None and st.global_step == 1


@pytest.mark.chaos
def test_manager_truncated_newest_falls_back(tmp_path):
    """Acceptance: a torn (truncated) arrays.bin fails validation and the
    previous checkpoint is used instead."""
    mgr = CheckpointManager(str(tmp_path), keep_last=0, async_write=False)
    mgr.save(_state(1), 1)
    mgr.save(_state(2), 2)
    arrays = tmp_path / "ckpt-00000002" / "arrays.bin"
    arrays.write_bytes(arrays.read_bytes()[:37])
    st = mgr.load_latest()
    assert st is not None and st.global_step == 1


@pytest.mark.chaos
def test_manager_missing_manifest_falls_back(tmp_path):
    """A crash between arrays.bin and manifest.json (the ckpt:post_arrays
    kill point) must leave an ignorable checkpoint."""
    mgr = CheckpointManager(str(tmp_path), keep_last=0, async_write=False)
    mgr.save(_state(1), 1)
    mgr.save(_state(2), 2)
    os.unlink(tmp_path / "ckpt-00000002" / "manifest.json")
    st = mgr.load_latest()
    assert st is not None and st.global_step == 1


def test_manager_all_invalid_returns_none(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_write=False)
    assert mgr.load_latest() is None
    mgr.save(_state(1), 1)
    os.unlink(tmp_path / "ckpt-00000001" / "manifest.json")
    assert mgr.load_latest() is None


def test_manager_reuse_clears_preempted(tmp_path):
    """A caller-supplied manager reused across fits must not carry a stale
    preemption flag into the next fit (which would abort it after one
    batch, looking like a completed run)."""
    mgr = CheckpointManager(str(tmp_path), async_write=False)
    mgr.preempted.set()
    mgr.install_signal_handlers()
    try:
        assert not mgr.preempted.is_set()
    finally:
        mgr.restore_signal_handlers()


def test_atomic_write_respects_umask(tmp_path):
    """mkstemp creates 0600; the committed file must get the umask-derived
    mode a plain open() would have produced."""
    path = str(tmp_path / "m.bin")
    old = os.umask(0o022)
    try:
        atomic_write_bytes(path, b"x")
    finally:
        os.umask(old)
    assert (os.stat(path).st_mode & 0o777) == 0o644


def test_manager_background_write_error_surfaces(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=0)
    bad = TrainingState({"arg:w": np.ones(2, np.float32)}, {"format": 1})
    bad.meta["unjsonable"] = object()  # manifest json.dumps will fail
    mgr.save(bad, 1)
    with pytest.raises(CheckpointError):
        mgr.flush()
    mgr.close()


# ---------------------------------------------------------------------------
# training-state capture/restore pieces
# ---------------------------------------------------------------------------

def test_optimizer_state_roundtrip():
    from mxnet_tpu.ndarray import array
    from mxnet_tpu.optimizer import create as opt_create
    from mxnet_tpu.optimizer.optimizer import Updater

    opt = opt_create("adam", learning_rate=0.01)
    upd = Updater(opt)
    w = array(np.ones((3, 2), np.float32))
    for _ in range(3):
        upd(0, array(np.full((3, 2), 0.1, np.float32)), w)
    st = capture_training_state(updater=upd, optimizer=opt)

    opt2 = opt_create("adam", learning_rate=0.01)
    upd2 = Updater(opt2)
    restore_optimizer(upd2, opt2, st)
    assert opt2.num_update == opt.num_update
    assert opt2._index_update_count == opt._index_update_count
    m1, v1 = upd.states[0][0].asnumpy(), upd.states[0][1].asnumpy()
    m2, v2 = upd2.states[0][0].asnumpy(), upd2.states[0][1].asnumpy()
    np.testing.assert_array_equal(m1, m2)
    np.testing.assert_array_equal(v1, v2)

    # bitwise: the next update must match on both replicas
    w2 = array(w.asnumpy())
    g = array(np.full((3, 2), 0.2, np.float32))
    upd(0, g, w)
    upd2(0, g, w2)
    np.testing.assert_array_equal(w.asnumpy(), w2.asnumpy())


def test_rng_state_roundtrip():
    np.random.seed(11)
    mx.random.seed(11)
    mx.random.uniform(shape=(2,))  # advance the key stream
    np.random.rand(3)              # advance the MT stream
    st = capture_training_state()

    a1 = np.random.rand(4)
    k1 = mx.random.uniform(shape=(3,)).asnumpy()

    np.random.seed(999)  # scramble, then restore
    mx.random.seed(999)
    restore_rng(st)
    np.testing.assert_array_equal(np.random.rand(4), a1)
    np.testing.assert_array_equal(mx.random.uniform(shape=(3,)).asnumpy(), k1)


def test_iterator_state_roundtrip():
    X = np.arange(40, dtype=np.float32).reshape(10, 4)
    y = np.arange(10, dtype=np.float32)
    it = NDArrayIter(X, y, batch_size=2, shuffle=True)
    it.reset()
    next(it)
    next(it)
    st = capture_training_state(train_data=it)
    remaining1 = [b.data[0].asnumpy() for b in it]

    it2 = NDArrayIter(X, y, batch_size=2, shuffle=True)
    from mxnet_tpu.checkpoint.state import restore_iterator

    assert restore_iterator(it2, st)
    remaining2 = [b.data[0].asnumpy() for b in it2]
    assert len(remaining1) == len(remaining2) == 3
    for a, b in zip(remaining1, remaining2):
        np.testing.assert_array_equal(a, b)


def test_trainer_checkpoint_state_roundtrip():
    from mxnet_tpu import gluon, nd

    net = gluon.nn.Dense(3)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    with mx.autograd.record():
        loss = net(nd.ones((2, 4))).sum()
    loss.backward()
    tr.step(2)
    st = tr.get_checkpoint_state()

    net2 = gluon.nn.Dense(3)
    net2.initialize()
    net2(nd.ones((2, 4)))
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9})
    tr2.set_checkpoint_state(st)
    assert tr2._optimizer.num_update == tr._optimizer.num_update
    for k, s in tr._updaters[0].states.items():
        s2 = tr2._updaters[0].states[k]
        np.testing.assert_array_equal(_leaf(s), _leaf(s2))


def _leaf(s):
    while isinstance(s, tuple):
        s = s[0]
    return s.asnumpy()


# ---------------------------------------------------------------------------
# Module.fit integration: split run == straight run, bitwise
# ---------------------------------------------------------------------------

def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _fit(num_epoch, ckpt=None, resume="never", seed=33):
    np.random.seed(seed)
    mx.random.seed(seed)
    rng = np.random.RandomState(4321)
    X = rng.randn(32, 6).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.float32)
    it = NDArrayIter(X, y, batch_size=8, shuffle=True)
    mod = Module(_mlp(), context=mx.cpu())
    mod.fit(it, num_epoch=num_epoch, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            checkpoint=ckpt, resume=resume, checkpoint_batch_period=3)
    return mod.get_params()[0]


def test_fit_split_training_bitwise(tmp_path):
    """2 epochs + resume for 2 more == 4 straight epochs, bit-for-bit: the
    checkpoint captures everything that matters (params, momentum, counters,
    RNG streams, iterator order)."""
    straight = _fit(4)
    _fit(2, ckpt=str(tmp_path), resume="auto")  # writes checkpoints
    resumed = _fit(4, ckpt=str(tmp_path), resume="auto")
    assert straight.keys() == resumed.keys()
    for n in straight:
        np.testing.assert_array_equal(straight[n].asnumpy(),
                                      resumed[n].asnumpy(), err_msg=n)


def test_fit_resume_never_ignores_checkpoints(tmp_path):
    _fit(2, ckpt=str(tmp_path), resume="auto")
    p1 = _fit(1, ckpt=None)
    p2 = _fit(1, ckpt=str(tmp_path), resume="never")
    for n in p1:
        np.testing.assert_array_equal(p1[n].asnumpy(), p2[n].asnumpy())


def test_fit_resume_pinned_step(tmp_path):
    _fit(2, ckpt=str(tmp_path), resume="auto")
    mgr = CheckpointManager(str(tmp_path))
    steps = mgr.list_steps()
    assert steps, "expected committed checkpoints"
    st = mgr.load(steps[0])
    assert st.global_step == steps[0]


def test_estimator_checkpoint_resume_fresh_net(tmp_path):
    """CheckpointHandler(resume_from_checkpoint=True) must restore into a
    FRESH net instance — structural param names, not gluon's auto-prefixed
    p.name (dense0_weight vs the restarted process's dense1_weight)."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.gluon.contrib.estimator.event_handler import \
        CheckpointHandler

    np.random.seed(3)
    mx.random.seed(3)
    X = np.random.randn(40, 6).astype(np.float32)
    y = np.random.randint(0, 3, 40).astype(np.float32)
    loader = gluon.data.DataLoader(gluon.data.ArrayDataset(X, y),
                                   batch_size=8)

    def make():
        np.random.seed(3)
        mx.random.seed(3)
        net = gluon.nn.Dense(3)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9})
        return net, Estimator(net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                              trainer=tr)

    net1, est1 = make()
    est1.fit(train_data=loader, epochs=2,
             event_handlers=[CheckpointHandler(str(tmp_path), batch_period=3)])
    p1 = {k: p.data().asnumpy()
          for k, p in net1._collect_params_with_prefix().items()}

    net2, est2 = make()  # fresh instance: different auto-prefix
    h = CheckpointHandler(str(tmp_path), resume_from_checkpoint=True)
    h.train_begin(est2)
    assert h.resumed_from is not None
    p2 = {k: p.data().asnumpy()
          for k, p in net2._collect_params_with_prefix().items()}
    assert p1.keys() == p2.keys()
    for k in p1:
        np.testing.assert_array_equal(p1[k], p2[k], err_msg=k)
    for k, s in est1.trainer._updaters[0].states.items():
        np.testing.assert_array_equal(
            _leaf(s), _leaf(est2.trainer._updaters[0].states[k]))


def test_feedforward_fit_checkpoint(tmp_path):
    from mxnet_tpu.model import FeedForward

    np.random.seed(7)
    mx.random.seed(7)
    X = np.random.randn(32, 6).astype(np.float32)
    y = np.random.randint(0, 4, 32).astype(np.float32)
    ff = FeedForward(_mlp(), num_epoch=2)
    ff.fit(X, y, checkpoint=str(tmp_path), resume="never")
    assert CheckpointManager(str(tmp_path)).list_steps()
