"""Round-3 op long-tail: multi-tensor optimizers, sync BN, deformable conv,
interleaved attention matmuls, image ops, random/sample/pdf ops, CTC loss,
linalg extras. Pattern follows the reference's per-op numeric tests
(tests/python/unittest/test_operator.py — TBV)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops.registry import get_op, _REGISTRY


def _fn(name):
    return get_op(name).fn


def test_registry_size():
    assert len(_REGISTRY) >= 470, len(_REGISTRY)


def test_no_registered_op_raises_notimplemented():
    """Every registered op has a real implementation: none may be a raise
    stub, i.e. have `raise NotImplementedError` as its first executable
    statement (VERDICT r3 item 8: Correlation was the last such stub).
    Conditional raises inside real implementations (e.g. jnp.round's out=
    rejection) are fine."""
    import ast
    import inspect
    import textwrap

    for name, op in _REGISTRY.items():
        try:
            src = textwrap.dedent(inspect.getsource(op.fn))
            tree = ast.parse(src)
        except (OSError, TypeError, SyntaxError):
            continue
        fn_def = next((n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))), None)
        if fn_def is None or not fn_def.body:
            continue
        body = fn_def.body
        # skip docstring
        if (isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)):
            body = body[1:]
        if not body:
            continue
        first = body[0]
        is_stub = (isinstance(first, ast.Raise)
                   and isinstance(first.exc, ast.Call)
                   and getattr(first.exc.func, "id", "")
                   == "NotImplementedError")
        assert not is_stub, f"{name} is a raise-only stub"


def test_correlation_matches_naive():
    """FlowNet Correlation vs a brute-force reference (multiply + abs-diff,
    kernels 1/3, strides, padding). Reference src/operator/correlation-inl.h
    semantics (TBV — mount empty)."""
    import math

    def ref_corr(d1, d2, ks, md, s1, s2, pad, mult=True):
        n, c, h, w = d1.shape
        kr = (ks - 1) // 2
        border = md + kr
        ph, pw = h + 2 * pad, w + 2 * pad
        oh = math.ceil((ph - 2 * border) / s1)
        ow = math.ceil((pw - 2 * border) / s1)
        ngr = md // s2
        ngw = 2 * ngr + 1
        p1 = np.zeros((n, c, ph, pw))
        p1[:, :, pad:pad + h, pad:pad + w] = d1
        p2 = np.zeros((n, c, ph, pw))
        p2[:, :, pad:pad + h, pad:pad + w] = d2
        out = np.zeros((n, ngw * ngw, oh, ow))
        for b in range(n):
            for i in range(oh):
                for j in range(ow):
                    y1, x1 = i * s1 + border, j * s1 + border
                    for pi in range(-ngr, ngr + 1):
                        for qi in range(-ngr, ngr + 1):
                            ch = (pi + ngr) * ngw + (qi + ngr)
                            y2, x2 = y1 + pi * s2, x1 + qi * s2
                            acc = 0.0
                            for u in range(-kr, kr + 1):
                                for v in range(-kr, kr + 1):
                                    for cc in range(c):
                                        a = p1[b, cc, y1 + u, x1 + v]
                                        inb = (0 <= y2 + u < ph
                                               and 0 <= x2 + v < pw)
                                        bb = p2[b, cc, y2 + u, x2 + v] \
                                            if inb else 0.0
                                        acc += a * bb if mult else abs(a - bb)
                            out[b, ch, i, j] = acc / (ks * ks * c)
        return out

    rng = np.random.RandomState(0)
    for (ks, md, s1, s2, pad, mult) in [(1, 2, 1, 1, 2, True),
                                        (3, 2, 1, 2, 3, True),
                                        (1, 1, 2, 1, 1, False)]:
        d1 = rng.rand(2, 3, 8, 9).astype(np.float32)
        d2 = rng.rand(2, 3, 8, 9).astype(np.float32)
        got = np.asarray(_fn("Correlation")(
            jnp.asarray(d1), jnp.asarray(d2), ks, md, s1, s2, pad, mult))
        want = ref_corr(d1, d2, ks, md, s1, s2, pad, mult)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_correlation_grads_flow():
    d1 = jnp.asarray(np.random.RandomState(1).rand(1, 2, 6, 6)
                     .astype(np.float32))
    d2 = jnp.asarray(np.random.RandomState(2).rand(1, 2, 6, 6)
                     .astype(np.float32))

    def loss(a, b):
        return (_fn("Correlation")(a, b, 1, 1, 1, 1, 1, True) ** 2).sum()

    g1, g2 = jax.grad(loss, argnums=(0, 1))(d1, d2)
    assert np.isfinite(np.asarray(g1)).all()
    assert np.asarray(g2).any()


# ---------------------------------------------------------------------- multi
def test_multi_sgd_matches_single():
    rng = np.random.RandomState(0)
    ws = [jnp.asarray(rng.rand(4, 3).astype(np.float32)) for _ in range(3)]
    gs = [jnp.asarray(rng.rand(4, 3).astype(np.float32)) for _ in range(3)]
    lrs, wds = [0.1, 0.2, 0.3], [0.0, 0.01, 0.1]
    flat = [x for pair in zip(ws, gs) for x in pair]
    outs = _fn("multi_sgd_update")(*flat, lrs=lrs, wds=wds, num_weights=3)
    for i in range(3):
        ref = _fn("sgd_update")(ws[i], gs[i], lr=lrs[i], wd=wds[i])
        np.testing.assert_allclose(np.asarray(outs[i]), np.asarray(ref),
                                   rtol=1e-6)


def test_multi_mp_sgd_mom_and_preloaded():
    rng = np.random.RandomState(1)
    n = 2
    ws = [jnp.asarray(rng.rand(5).astype(np.float16)) for _ in range(n)]
    gs = [jnp.asarray(rng.rand(5).astype(np.float16)) for _ in range(n)]
    ms = [jnp.zeros(5, jnp.float32) for _ in range(n)]
    w32 = [w.astype(jnp.float32) for w in ws]
    flat = [x for grp in zip(ws, gs, ms, w32) for x in grp]
    outs = _fn("multi_mp_sgd_mom_update")(*flat, lrs=[0.1, 0.2],
                                          wds=[0.0, 0.0], momentum=0.9,
                                          num_weights=n)
    assert len(outs) == 3 * n
    assert outs[0].dtype == jnp.float16          # updated weights first
    assert outs[2 * n].dtype == jnp.float32      # then mom, then w32
    # preloaded variant: lrs/wds as device arrays
    flat2 = [x for pair in zip(ws, gs) for x in pair]
    pre = _fn("preloaded_multi_sgd_update")(
        *flat2, jnp.asarray([0.1, 0.2], jnp.float32),
        jnp.asarray([0.0, 0.0], jnp.float32), num_weights=n)
    ref = _fn("sgd_update")(ws[1], gs[1], lr=0.2)
    np.testing.assert_allclose(np.asarray(pre[1], np.float32),
                               np.asarray(ref, np.float32), rtol=1e-2)


def test_multi_lamb_phases():
    rng = np.random.RandomState(2)
    w = jnp.asarray(rng.rand(6).astype(np.float32))
    g = jnp.asarray(rng.rand(6).astype(np.float32))
    m = jnp.zeros(6)
    v = jnp.zeros(6)
    outs = _fn("multi_lamb_update_phase1")(w, g, m, v, num_weights=1,
                                           wds=[0.01], step_count=1)
    upd, m1, v1 = outs
    ref = _fn("lamb_update_phase1")(w, g, m, v, wd=0.01, t=1)
    np.testing.assert_allclose(np.asarray(upd), np.asarray(ref), rtol=1e-6)
    r1 = jnp.linalg.norm(w).reshape(1)
    r2 = jnp.linalg.norm(upd).reshape(1)
    w2 = _fn("multi_lamb_update_phase2")(w, upd, r1, r2, lrs=[0.01],
                                         num_weights=1)
    ref2 = _fn("lamb_update_phase2")(w, ref, r1, r2, lr=0.01)
    np.testing.assert_allclose(np.asarray(w2), np.asarray(ref2), rtol=1e-6)


# ------------------------------------------------------------------- sync BN
def test_sync_batch_norm_single_matches_bn():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.rand(4, 3, 5, 5).astype(np.float32))
    gamma = jnp.ones(3)
    beta = jnp.zeros(3)
    mm = jnp.zeros(3)
    mv = jnp.ones(3)
    out = _fn("_contrib_SyncBatchNorm")(x, gamma, beta, mm, mv,
                                        fix_gamma=False, _train=True)
    ref = _fn("BatchNorm")(x, gamma, beta, mm, mv, fix_gamma=False,
                           _train=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_sync_batch_norm_cross_device_stats():
    """Under shard_map over dp, stats must be the GLOBAL batch moments."""
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    devs = jax.devices()[:2]
    mesh = Mesh(np.array(devs), ("dp",))
    rng = np.random.RandomState(4)
    x = rng.rand(8, 3, 4, 4).astype(np.float32)
    gamma = jnp.ones(3)
    beta = jnp.zeros(3)
    mm = jnp.zeros(3)
    mv = jnp.ones(3)

    def f(xs):
        return _fn("_contrib_SyncBatchNorm")(xs, gamma, beta, mm, mv,
                                             fix_gamma=False, _train=True,
                                             output_mean_var=True)

    out, mean, var = jax.jit(shard_map(
        f, mesh=mesh, in_specs=P("dp"),
        out_specs=(P("dp"), P(), P())))(jnp.asarray(x))
    exp_mean = x.mean(axis=(0, 2, 3))
    np.testing.assert_allclose(np.asarray(mean), exp_mean, atol=1e-5)
    # global-stat normalization differs from per-shard BN
    ref_global = (x - exp_mean.reshape(1, 3, 1, 1)) / np.sqrt(
        x.var(axis=(0, 2, 3)).reshape(1, 3, 1, 1) + 1e-3)
    np.testing.assert_allclose(np.asarray(out), ref_global, atol=1e-4)


# ------------------------------------------------------------- deformable
def test_deformable_conv_zero_offset_is_conv():
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.rand(2, 4, 8, 8).astype(np.float32))
    w = jnp.asarray(rng.rand(6, 4, 3, 3).astype(np.float32))
    off = jnp.zeros((2, 18, 8, 8), jnp.float32)
    out = _fn("_contrib_DeformableConvolution")(
        x, off, w, None, kernel=(3, 3), pad=(1, 1), num_filter=6,
        no_bias=True)
    ref = jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_deformable_conv_integer_offset_shifts():
    """Offset (0, +1) everywhere == sampling input shifted left by one."""
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.rand(1, 1, 6, 6).astype(np.float32))
    w = jnp.ones((1, 1, 1, 1), jnp.float32)
    off = jnp.zeros((1, 2, 6, 6), jnp.float32).at[:, 1].set(1.0)
    out = _fn("_contrib_DeformableConvolution")(
        x, off, w, None, kernel=(1, 1), num_filter=1, no_bias=True)
    shifted = np.zeros((1, 1, 6, 6), np.float32)
    shifted[..., :, :-1] = np.asarray(x)[..., :, 1:]
    np.testing.assert_allclose(np.asarray(out), shifted, atol=1e-5)


def test_modulated_deformable_conv_mask_scales():
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.rand(1, 2, 5, 5).astype(np.float32))
    w = jnp.asarray(rng.rand(3, 2, 3, 3).astype(np.float32))
    off = jnp.zeros((1, 18, 5, 5), jnp.float32)
    mask = jnp.full((1, 9, 5, 5), 0.5, jnp.float32)
    out = _fn("_contrib_ModulatedDeformableConvolution")(
        x, off, mask, w, None, kernel=(3, 3), pad=(1, 1), num_filter=3,
        no_bias=True)
    ref = 0.5 * np.asarray(jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4)


# ---------------------------------------------------------- interleaved att
def test_interleaved_selfatt_matches_manual():
    rng = np.random.RandomState(8)
    S, B, H, hd = 6, 2, 2, 4
    qkv = rng.rand(S, B, H * 3 * hd).astype(np.float32)
    scores = _fn("_contrib_interleaved_matmul_selfatt_qk")(
        jnp.asarray(qkv), heads=H)
    assert scores.shape == (B * H, S, S)
    x = qkv.reshape(S, B, H, 3, hd)
    q = np.moveaxis(x[:, :, :, 0], 0, 2).reshape(B * H, S, hd)
    k = np.moveaxis(x[:, :, :, 1], 0, 2).reshape(B * H, S, hd)
    ref = np.einsum("nqd,nkd->nqk", q, k) / np.sqrt(hd)
    np.testing.assert_allclose(np.asarray(scores), ref, atol=1e-5)

    att = jax.nn.softmax(scores, axis=-1)
    out = _fn("_contrib_interleaved_matmul_selfatt_valatt")(
        jnp.asarray(qkv), att, heads=H)
    assert out.shape == (S, B, H * hd)
    v = np.moveaxis(x[:, :, :, 2], 0, 2).reshape(B * H, S, hd)
    ref_o = np.einsum("nqk,nkd->nqd", np.asarray(att), v)
    ref_o = np.moveaxis(ref_o.reshape(B, H, S, hd), 2, 0).reshape(S, B, H * hd)
    np.testing.assert_allclose(np.asarray(out), ref_o, atol=1e-5)


def test_interleaved_encdec_roundtrip():
    rng = np.random.RandomState(9)
    Sq, Sk, B, H, hd = 3, 5, 2, 2, 4
    q = rng.rand(Sq, B, H * hd).astype(np.float32)
    kv = rng.rand(Sk, B, H * 2 * hd).astype(np.float32)
    scores = _fn("_contrib_interleaved_matmul_encdec_qk")(
        jnp.asarray(q), jnp.asarray(kv), heads=H)
    assert scores.shape == (B * H, Sq, Sk)
    att = jax.nn.softmax(scores, axis=-1)
    out = _fn("_contrib_interleaved_matmul_encdec_valatt")(
        jnp.asarray(kv), att, heads=H)
    assert out.shape == (Sq, B, H * hd)
    assert np.isfinite(np.asarray(out)).all()


# ----------------------------------------------------------------- image ops
def test_image_ops_basic():
    rng = np.random.RandomState(10)
    img = jnp.asarray(rng.randint(0, 255, (8, 10, 3)).astype(np.uint8))
    t = _fn("_image_to_tensor")(img)
    assert t.shape == (3, 8, 10) and t.dtype == jnp.float32
    assert float(t.max()) <= 1.0
    n = _fn("_image_normalize")(t, mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2))
    np.testing.assert_allclose(np.asarray(n),
                               (np.asarray(t) - 0.5) / 0.2, atol=1e-6)
    f = _fn("_image_flip_left_right")(img)
    np.testing.assert_array_equal(np.asarray(f), np.asarray(img)[:, ::-1])
    c = _fn("_image_crop")(img, x=2, y=1, width=4, height=3)
    assert c.shape == (3, 4, 3)
    r = _fn("_image_resize")(img, size=5)
    assert r.shape == (5, 5, 3)
    r2 = _fn("_image_resize")(img, size=8, keep_ratio=True)
    assert r2.shape == (8, 10, 3)


def test_image_random_ops_seeded():
    mx.random.seed(42)
    rng = np.random.RandomState(11)
    img = jnp.asarray(rng.rand(6, 6, 3).astype(np.float32))
    b = _fn("_image_random_brightness")(img, min_factor=0.5, max_factor=1.5)
    assert b.shape == img.shape
    s = _fn("_image_random_saturation")(img, min_factor=0.5, max_factor=1.5)
    assert np.isfinite(np.asarray(s)).all()
    h = _fn("_image_random_hue")(img, min_factor=-0.1, max_factor=0.1)
    assert np.isfinite(np.asarray(h)).all()
    j = _fn("_image_random_color_jitter")(img, brightness=0.1, contrast=0.1,
                                          saturation=0.1, hue=0.1)
    assert j.shape == img.shape
    li = _fn("_image_random_lighting")(img, alpha_std=0.05)
    assert li.shape == img.shape
    # hue with zero range is identity-ish (rotation by 0)
    h0 = _fn("_image_random_hue")(img, min_factor=0.0, max_factor=0.0)
    np.testing.assert_allclose(np.asarray(h0), np.asarray(img), atol=1e-5)


# ---------------------------------------------------------------- random ops
def test_random_ops_shapes_and_stats():
    mx.random.seed(0)
    u = _fn("_random_uniform")(low=2.0, high=4.0, shape=(2000,))
    assert u.shape == (2000,)
    assert 2.0 <= float(u.min()) and float(u.max()) <= 4.0
    assert abs(float(u.mean()) - 3.0) < 0.1
    n = _fn("_random_normal")(loc=1.0, scale=2.0, shape=(4000,))
    assert abs(float(n.mean()) - 1.0) < 0.15
    g = _fn("_random_gamma")(alpha=3.0, beta=2.0, shape=(4000,))
    assert abs(float(g.mean()) - 6.0) < 0.5      # E = alpha*beta
    e = _fn("_random_exponential")(lam=2.0, shape=(4000,))
    assert abs(float(e.mean()) - 0.5) < 0.1
    p = _fn("_random_poisson")(lam=3.0, shape=(2000,))
    assert abs(float(p.mean()) - 3.0) < 0.3
    ri = _fn("_random_randint")(low=0, high=10, shape=(100,))
    assert int(ri.min()) >= 0 and int(ri.max()) < 10


def test_sample_ops_tensor_params():
    mx.random.seed(1)
    lo = jnp.asarray([0.0, 10.0])
    hi = jnp.asarray([1.0, 20.0])
    s = _fn("_sample_uniform")(lo, hi, shape=(500,))
    assert s.shape == (2, 500)
    assert float(s[0].max()) <= 1.0 and float(s[1].min()) >= 10.0
    mu = jnp.asarray([0.0, 100.0])
    sd = jnp.asarray([1.0, 1.0])
    sn = _fn("_sample_normal")(mu, sd, shape=(500,))
    assert abs(float(sn[1].mean()) - 100.0) < 1.0
    probs = jnp.asarray([[0.0, 1.0, 0.0], [1.0, 0.0, 0.0]])
    m = _fn("_sample_multinomial")(probs, shape=(50,))
    assert m.shape == (2, 50)
    np.testing.assert_array_equal(np.asarray(m[0]), np.ones(50))
    np.testing.assert_array_equal(np.asarray(m[1]), np.zeros(50))
    x = jnp.arange(10.0)
    sh = _fn("_shuffle")(x)
    np.testing.assert_allclose(np.sort(np.asarray(sh)), np.asarray(x))


def test_pdf_ops_known_values():
    # N(0,1) at 0: 1/sqrt(2pi)
    pdf = _fn("_random_pdf_normal")(jnp.zeros((1, 1)), jnp.zeros(1),
                                    jnp.ones(1))
    np.testing.assert_allclose(float(pdf[0, 0]), 1 / np.sqrt(2 * np.pi),
                               rtol=1e-5)
    # U(0,2) density inside/outside
    u = _fn("_random_pdf_uniform")(jnp.asarray([[0.5, 3.0]]), jnp.zeros(1),
                                   jnp.full(1, 2.0))
    np.testing.assert_allclose(np.asarray(u), [[0.5, 0.0]], atol=1e-6)
    # exponential(lam=2) at 0: pdf = 2
    e = _fn("_random_pdf_exponential")(jnp.zeros((1, 1)), jnp.full(1, 2.0))
    np.testing.assert_allclose(float(e[0, 0]), 2.0, rtol=1e-5)
    # poisson pmf at k=0, lam=1 -> exp(-1)
    p = _fn("_random_pdf_poisson")(jnp.zeros((1, 1)), jnp.ones(1))
    np.testing.assert_allclose(float(p[0, 0]), np.exp(-1), rtol=1e-5)
    # gamma(alpha=1, beta=1) == exponential(1): pdf(x)=exp(-x)
    g = _fn("_random_pdf_gamma")(jnp.full((1, 1), 0.7), jnp.ones(1),
                                 jnp.ones(1))
    np.testing.assert_allclose(float(g[0, 0]), np.exp(-0.7), rtol=1e-4)


# ------------------------------------------------------------------ ctc loss
def test_ctc_loss_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(12)
    T, B, C, L = 10, 3, 5, 4
    acts = rng.randn(T, B, C).astype(np.float32)
    labels = rng.randint(1, C, (B, L)).astype(np.float32)  # blank=0 → 1-based
    label_lens = np.array([4, 2, 3])
    lab_padded = labels.copy()
    for i, ll in enumerate(label_lens):
        lab_padded[i, ll:] = 0  # padding value for blank_label="first"

    loss, logprobs = _fn("ctc_loss")(jnp.asarray(acts),
                                     jnp.asarray(lab_padded))
    t_loss = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.tensor(acts), dim=-1),
        torch.tensor(labels.astype(np.int64)),
        torch.full((B,), T, dtype=torch.long),
        torch.tensor(label_lens), blank=0, reduction="none")
    np.testing.assert_allclose(np.asarray(loss), t_loss.numpy(), rtol=1e-4)
    assert logprobs.shape == (T, B, C)


def test_ctc_loss_variable_data_lengths():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(13)
    T, B, C = 8, 2, 4
    acts = rng.randn(T, B, C).astype(np.float32)
    labels = np.array([[1, 2, 0], [3, 0, 0]], np.float32)
    data_lens = np.array([8, 5])
    loss, _ = _fn("ctc_loss")(jnp.asarray(acts), jnp.asarray(labels),
                              jnp.asarray(data_lens), None,
                              use_data_lengths=True)
    # torch takes concatenated targets: row0=[1,2], row1=[3]
    t_loss = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.tensor(acts), dim=-1),
        torch.tensor(np.array([1, 2, 3], dtype=np.int64)),
        torch.tensor(data_lens), torch.tensor([2, 1]),
        blank=0, reduction="none")
    np.testing.assert_allclose(np.asarray(loss), t_loss.numpy(), rtol=1e-4)


def test_ctc_loss_grad_finite():
    rng = np.random.RandomState(14)
    acts = jnp.asarray(rng.randn(6, 2, 4).astype(np.float32))
    labels = jnp.asarray(np.array([[1, 2], [3, 1]], np.float32))

    def f(a):
        loss, _ = _fn("ctc_loss")(a, labels)
        return jnp.sum(loss)

    g = jax.grad(f)(acts)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


# -------------------------------------------------------------- linalg extra
def test_linalg_extras():
    rng = np.random.RandomState(15)
    a = rng.rand(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
    det = _fn("_linalg_det")(jnp.asarray(a))
    np.testing.assert_allclose(float(det), np.linalg.det(a), rtol=1e-4)
    sign, logdet = _fn("_linalg_slogdet")(jnp.asarray(a))
    np.testing.assert_allclose(float(sign) * np.exp(float(logdet)),
                               np.linalg.det(a), rtol=1e-4)
    inv = _fn("_linalg_inverse")(jnp.asarray(a))
    np.testing.assert_allclose(np.asarray(inv) @ a, np.eye(3), atol=1e-4)
    d = _fn("_linalg_extractdiag")(jnp.asarray(a))
    np.testing.assert_allclose(np.asarray(d), np.diag(a), rtol=1e-6)
    md = _fn("_linalg_makediag")(jnp.asarray(np.array([1.0, 2.0])))
    np.testing.assert_allclose(np.asarray(md), np.diag([1.0, 2.0]))
    lo = _fn("_linalg_extracttrian")(jnp.asarray(a))
    assert lo.shape == (6,)
    back = _fn("_linalg_maketrian")(lo)
    np.testing.assert_allclose(np.asarray(back), np.tril(a), atol=1e-6)
    tr = _fn("_linalg_trmm")(jnp.asarray(a), jnp.asarray(a))
    np.testing.assert_allclose(np.asarray(tr), np.tril(a) @ a, rtol=1e-4)


def test_misc_tensor_ops():
    x = jnp.asarray(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(np.asarray(_fn("cumsum")(x, axis=1)),
                               np.cumsum(np.asarray(x), axis=1))
    np.testing.assert_allclose(np.asarray(_fn("cumprod")(x + 1, axis=0)),
                               np.cumprod(np.asarray(x) + 1, axis=0))
    bt = _fn("batch_take")(x, jnp.asarray([2, 0]))
    np.testing.assert_allclose(np.asarray(bt), [2.0, 3.0])
    # contrib sundries
    q = _fn("_contrib_quadratic")(x, a=1.0, b=2.0, c=3.0)
    np.testing.assert_allclose(np.asarray(q),
                               np.asarray(x) ** 2 + 2 * np.asarray(x) + 3)
    gm = _fn("_contrib_gradientmultiplier")
    gr = jax.grad(lambda t: jnp.sum(gm(t, scalar=-2.0)))(x)
    np.testing.assert_allclose(np.asarray(gr), -2.0 * np.ones((2, 3)))
    rs = _fn("_contrib_BilinearResize2D")(x.reshape(1, 1, 2, 3), height=4,
                                          width=6)
    assert rs.shape == (1, 1, 4, 6)
    ap = _fn("_contrib_AdaptiveAvgPooling2D")(
        jnp.ones((1, 2, 6, 6)), output_size=3)
    assert ap.shape == (1, 2, 3, 3)
    np.testing.assert_allclose(np.asarray(ap), np.ones((1, 2, 3, 3)))
    ap2 = _fn("_contrib_AdaptiveAvgPooling2D")(
        jnp.ones((1, 1, 5, 7)), output_size=(3, 4))
    assert ap2.shape == (1, 1, 3, 4)
    np.testing.assert_allclose(np.asarray(ap2), np.ones((1, 1, 3, 4)),
                               atol=1e-6)


def test_nd_image_namespace_and_gluon_sync_bn():
    from mxnet_tpu import nd

    rng = np.random.RandomState(16)
    img = nd.array(rng.randint(0, 255, (4, 5, 3)).astype(np.uint8))
    t = nd.image.to_tensor(img)
    assert t.shape == (3, 4, 5)
    flipped = nd.image.flip_left_right(img)
    np.testing.assert_array_equal(flipped.asnumpy(),
                                  img.asnumpy()[:, ::-1])

    from mxnet_tpu.gluon.contrib.nn import SyncBatchNorm

    net = SyncBatchNorm(in_channels=3)
    net.initialize()
    x = nd.array(rng.rand(2, 3, 4, 4).astype(np.float32))
    with mx.autograd.record():
        out = net(x)
    assert out.shape == x.shape
    # running stats moved off their init after a training-mode pass
    assert float(np.abs(net.running_mean.data().asnumpy()).sum()) > 0


def test_sync_bn_layer_in_sharded_trainer():
    """SyncBatchNorm inside ShardedTrainer: global-batch stats on a dp mesh."""
    from mxnet_tpu import gluon, nd
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.nn import SyncBatchNorm

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, 3, padding=1, in_channels=3))
        net.add(SyncBatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Flatten())
        net.add(nn.Dense(2))
    net.initialize()
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = par.make_mesh({"dp": 2}, devices=jax.devices()[:2])
    trainer = par.ShardedTrainer(net, loss_fn, mesh, optimizer="sgd",
                                 optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(17)
    x = nd.array(rng.rand(8, 3, 6, 6).astype(np.float32))
    y = nd.array(rng.randint(0, 2, 8).astype(np.float32))
    losses = [float(trainer.step(x, y).asnumpy()) for _ in range(4)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_linalg_trian_offsets_roundtrip():
    rng = np.random.RandomState(18)
    a = rng.rand(3, 3).astype(np.float32)
    for off in (1, -1, 2):
        packed = _fn("_linalg_extracttrian")(jnp.asarray(a), offset=off)
        ref = (np.triu(a, off) if off > 0 else np.tril(a, off))
        back = _fn("_linalg_maketrian")(packed, offset=off)
        np.testing.assert_allclose(np.asarray(back), ref, atol=1e-6)


def test_multi_lamb_per_group_step_count():
    rng = np.random.RandomState(19)
    ws = [jnp.asarray(rng.rand(4).astype(np.float32)) for _ in range(2)]
    gs = [jnp.asarray(rng.rand(4).astype(np.float32)) for _ in range(2)]
    ms = [jnp.zeros(4) for _ in range(2)]
    vs = [jnp.zeros(4) for _ in range(2)]
    flat = [x for grp in zip(ws, gs, ms, vs) for x in grp]
    outs = _fn("multi_lamb_update_phase1")(*flat, num_weights=2,
                                           wds=[0.0, 0.0],
                                           step_count=(3, 7))
    for i, t in enumerate((3, 7)):
        ref = _fn("lamb_update_phase1")(ws[i], gs[i], ms[i], vs[i], t=t)
        np.testing.assert_allclose(np.asarray(outs[i]), np.asarray(ref),
                                   rtol=1e-6)
