"""Legacy mx.rnn cell API (reference python/mxnet/rnn/ — the
BucketingModule companion): unfused cells vs the fused RNN op, and
BucketSentenceIter bucketing."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops.rnn import rnn_param_size


def _pack_lstm(i2h_w, h2h_w, i2h_b, h2h_b):
    return np.concatenate([i2h_w.reshape(-1), h2h_w.reshape(-1),
                           i2h_b, h2h_b]).astype(np.float32)


def test_lstm_cell_matches_fused():
    """Unrolled LSTMCell == fused nd.RNN given packed weights (same cuDNN
    gate order)."""
    rng = np.random.RandomState(0)
    T, N, C, H = 4, 2, 3, 5
    i2h_w = rng.randn(4 * H, C).astype(np.float32) * 0.3
    h2h_w = rng.randn(4 * H, H).astype(np.float32) * 0.3
    i2h_b = rng.randn(4 * H).astype(np.float32) * 0.1
    h2h_b = rng.randn(4 * H).astype(np.float32) * 0.1
    x = rng.randn(N, T, C).astype(np.float32)

    cell = mx.rnn.LSTMCell(H, prefix="l0_")
    data = mx.sym.Variable("data")
    outs, _ = cell.unroll(T, data, merge_outputs=True)
    got = outs.eval(data=nd.array(x),
                    l0_i2h_weight=nd.array(i2h_w),
                    l0_h2h_weight=nd.array(h2h_w),
                    l0_i2h_bias=nd.array(i2h_b),
                    l0_h2h_bias=nd.array(h2h_b))
    got0 = (got[0] if isinstance(got, (list, tuple)) else got).asnumpy()

    params = _pack_lstm(i2h_w, h2h_w, i2h_b, h2h_b)
    assert params.size == rnn_param_size("lstm", C, H)
    fused = nd.RNN(nd.array(x.transpose(1, 0, 2)), nd.array(params),
                   nd.zeros((1, N, H)), nd.zeros((1, N, H)),
                   state_size=H, num_layers=1, mode="lstm")
    np.testing.assert_allclose(got0, fused.asnumpy().transpose(1, 0, 2),
                               rtol=2e-5, atol=2e-6)


def test_gru_cell_matches_fused():
    rng = np.random.RandomState(1)
    T, N, C, H = 3, 2, 4, 3
    i2h_w = rng.randn(3 * H, C).astype(np.float32) * 0.3
    h2h_w = rng.randn(3 * H, H).astype(np.float32) * 0.3
    i2h_b = rng.randn(3 * H).astype(np.float32) * 0.1
    h2h_b = rng.randn(3 * H).astype(np.float32) * 0.1
    x = rng.randn(N, T, C).astype(np.float32)

    cell = mx.rnn.GRUCell(H, prefix="g0_")
    data = mx.sym.Variable("data")
    outs, _ = cell.unroll(T, data, merge_outputs=True)
    got = outs.eval(data=nd.array(x),
                    g0_i2h_weight=nd.array(i2h_w),
                    g0_h2h_weight=nd.array(h2h_w),
                    g0_i2h_bias=nd.array(i2h_b),
                    g0_h2h_bias=nd.array(h2h_b))
    got0 = (got[0] if isinstance(got, (list, tuple)) else got).asnumpy()
    params = _pack_lstm(i2h_w, h2h_w, i2h_b, h2h_b)
    fused = nd.RNN(nd.array(x.transpose(1, 0, 2)), nd.array(params),
                   nd.zeros((1, N, H)), state_size=H, num_layers=1,
                   mode="gru")
    np.testing.assert_allclose(got0, fused.asnumpy().transpose(1, 0, 2),
                               rtol=2e-5, atol=2e-6)


def test_sequential_and_dropout_cells():
    cell = mx.rnn.SequentialRNNCell()
    cell.add(mx.rnn.RNNCell(4, prefix="r0_"))
    cell.add(mx.rnn.DropoutCell(0.0))
    cell.add(mx.rnn.RNNCell(3, prefix="r1_"))
    data = mx.sym.Variable("data")
    outs, states = cell.unroll(3, data, merge_outputs=True)
    args = set(outs.list_arguments())
    assert {"r0_i2h_weight", "r1_i2h_weight"} <= args
    assert len(states) == 2


def test_fused_rnn_cell_unroll():
    rng = np.random.RandomState(2)
    T, N, C, H = 3, 2, 4, 5
    cell = mx.rnn.FusedRNNCell(H, num_layers=2, mode="lstm", prefix="f_")
    data = mx.sym.Variable("data")
    out, _ = cell.unroll(T, data, layout="NTC")
    n_p = rnn_param_size("lstm", C, H, num_layers=2)
    x = rng.randn(N, T, C).astype(np.float32)
    res = out.eval(data=nd.array(x),
                   f_parameters=nd.array(rng.randn(n_p).astype(np.float32)
                                         * 0.2))
    r0 = (res[0] if isinstance(res, (list, tuple)) else res)
    assert r0.shape == (N, T, H)
    assert np.isfinite(r0.asnumpy()).all()


def test_bucket_sentence_iter():
    rng = np.random.RandomState(3)
    sentences = [list(rng.randint(1, 50, rng.randint(2, 12)))
                 for _ in range(200)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=8,
                                   buckets=[4, 8, 12], invalid_label=-1)
    seen = 0
    for batch in it:
        blen = batch.bucket_key
        assert blen in (4, 8, 12)
        assert batch.data[0].shape == (8, blen)
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        # label is the next-token shift wherever data continues
        mask = d[:, 1:] != -1
        np.testing.assert_array_equal(l[:, :-1][mask], d[:, 1:][mask])
        seen += 1
    assert seen >= 3
    it.reset()
    assert next(iter(it)) is not None


def test_manual_stepping_and_final_states():
    # manual per-step pattern with None begin states must work
    cell = mx.rnn.LSTMCell(3, prefix="m_")
    x_t = mx.sym.Variable("x")
    states = cell.begin_state()
    out, states = cell(x_t, states)
    out2, _ = cell(out, states)
    assert "m_i2h_weight" in out2.list_arguments()

    # FusedRNNCell returns REAL final states, not the zeros it started with
    rng = np.random.RandomState(5)
    T, N, C, H = 3, 2, 4, 3
    f = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="ff_")
    data = mx.sym.Variable("data")
    out, states = f.unroll(T, data, layout="NTC")
    n_p = rnn_param_size("lstm", C, H)
    feed = dict(data=nd.array(rng.randn(N, T, C).astype(np.float32)),
                ff_parameters=nd.array(rng.randn(n_p).astype(np.float32)
                                       * 0.3))
    h_final = states[0].eval(**feed)
    h0 = (h_final[0] if isinstance(h_final, (list, tuple)) else h_final)
    assert np.abs(h0.asnumpy()).max() > 0, "final states are the zero init"
    # final h equals the last output step
    y = out.eval(**feed)
    y0 = (y[0] if isinstance(y, (list, tuple)) else y).asnumpy()
    np.testing.assert_allclose(h0.asnumpy()[0], y0[:, -1], rtol=1e-5)


def test_fused_cell_pack_unpack_roundtrip():
    rng = np.random.RandomState(6)
    C, H, L = 4, 3, 2
    f = mx.rnn.FusedRNNCell(H, num_layers=L, mode="lstm", prefix="p_")
    n_p = rnn_param_size("lstm", C, H, num_layers=L)
    vec = rng.randn(n_p).astype(np.float32)
    un = f.unpack_weights({"p_parameters": vec}, input_size=C)
    assert un["p_l0_i2h_weight"].shape == (4 * H, C)
    assert un["p_l1_i2h_weight"].shape == (4 * H, H)
    re = f.pack_weights(un)
    np.testing.assert_array_equal(re["p_parameters"], vec)


def test_bucket_iter_empty_buckets_raises():
    import pytest as _pt

    with _pt.raises(ValueError, match="no buckets"):
        mx.rnn.BucketSentenceIter([[1, 2, 3]], batch_size=8, buckets=None)


def test_bucketing_lm_end_to_end():
    """The classic reference workflow: mx.rnn cells + BucketSentenceIter +
    BucketingModule.fit-style loop (example/rnn/bucketing — TBV)."""
    from mxnet_tpu.module import BucketingModule

    # seed EVERYTHING: init draws from the framework RNG and the bucket
    # iterator shuffles via global numpy — full-suite ordering otherwise
    # makes this toy 3-epoch convergence check flaky
    mx.random.seed(11)
    np.random.seed(11)
    rng = np.random.RandomState(7)
    V, E, H = 20, 6, 5
    sentences = [list(rng.randint(1, V, rng.randint(3, 9)))
                 for _ in range(120)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=4, buckets=[4, 8],
                                   invalid_label=0)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        emb = mx.sym.Embedding(data, input_dim=V, output_dim=E,
                               name="embed")
        cell = mx.rnn.LSTMCell(H, prefix="l0_")
        outputs, _ = cell.unroll(seq_len, emb, layout="NTC",
                                 merge_outputs=True)
        pred = mx.sym.FullyConnected(outputs, num_hidden=V, flatten=False,
                                     name="pred")
        pred = mx.sym.reshape(pred, shape=(-1, V))
        out = mx.sym.SoftmaxOutput(pred, mx.sym.reshape(label, shape=(-1,)),
                                   name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = BucketingModule(sym_gen, default_bucket_key=8)
    mod.bind([("data", (4, 8))], [("softmax_label", (4, 8))])
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.5})

    losses = []
    for epoch in range(5):
        it.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            out = mod.get_outputs()[0].asnumpy()
            lbl = batch.label[0].asnumpy().reshape(-1).astype(int)
            p = out[np.arange(len(lbl)), lbl]
            losses.append(float(-np.log(np.maximum(p, 1e-9)).mean()))
            mod.backward()
            mod.update()
    assert np.isfinite(losses).all()
    # training must actually reduce NLL on this toy corpus
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1, \
        f"no learning: first {np.mean(losses[:5]):.3f} " \
        f"last {np.mean(losses[-5:]):.3f}"


def test_modifier_and_bidirectional_cells():
    rng = np.random.RandomState(8)
    T, N, C, H = 3, 2, 5, 5
    x = rng.randn(N, T, C).astype(np.float32)
    data = mx.sym.Variable("data")

    res = mx.rnn.ResidualCell(mx.rnn.RNNCell(H, prefix="res_"))
    outs, _ = res.unroll(T, data, merge_outputs=True)
    feed = {"data": nd.array(x),
            "res_i2h_weight": nd.array(rng.randn(H, C).astype(np.float32) * 0.2),
            "res_i2h_bias": nd.zeros((H,)),
            "res_h2h_weight": nd.array(rng.randn(H, H).astype(np.float32) * 0.2),
            "res_h2h_bias": nd.zeros((H,))}
    got = outs.eval(**{k: v for k, v in feed.items()})
    g0 = (got[0] if isinstance(got, (list, tuple)) else got).asnumpy()
    assert g0.shape == (N, T, H) and np.isfinite(g0).all()

    bi = mx.rnn.BidirectionalCell(mx.rnn.RNNCell(3, prefix="fw_"),
                                  mx.rnn.RNNCell(3, prefix="bw_"))
    outs, states = bi.unroll(T, data, merge_outputs=True)
    assert len(states) == 2
    args = set(outs.list_arguments())
    assert {"fw_i2h_weight", "bw_i2h_weight"} <= args

    zo = mx.rnn.ZoneoutCell(mx.rnn.RNNCell(4, prefix="zo_"),
                            zoneout_states=0.3)
    outs, _ = zo.unroll(T, data, merge_outputs=True)
    assert "zo_i2h_weight" in outs.list_arguments()


def test_zoneout_output_blend_tracks_prev_output():
    """Output zoneout is the expectation blend prev*p + next*(1-p) with the
    previous step's (blended) output — not an attenuating out*(1-p)
    (ADVICE.md). Verified against a hand-rolled recurrence."""
    p = 0.4
    base = mx.rnn.RNNCell(3, prefix="zob_")
    zo = mx.rnn.ZoneoutCell(base, zoneout_outputs=p)
    T, B, C = 4, 2, 3
    rng = np.random.RandomState(3)
    xs = rng.randn(T, B, C).astype(np.float32)
    args = None
    # reference recurrence: run the BASE cell manually, blend outputs
    states = base.begin_state()
    base_outs = []
    shapes = {}
    sym_steps = []
    x_syms = [mx.sym.Variable(f"x{t}") for t in range(T)]
    st = base.begin_state()
    for t in range(T):
        o, st = base(x_syms[t], st)
        sym_steps.append(o)
    grp = mx.sym.Group(sym_steps)
    import numpy as onp
    feed = {f"x{t}": xs[t] for t in range(T)}
    warg = {n: rng.randn(*s).astype(np.float32) * 0.3
            for n, s in zip(grp.list_arguments(),
                            grp.infer_shape(**{f"x{t}": (B, C)
                                               for t in range(T)})[0])
            if not n.startswith("x")}
    exe = grp.simple_bind(grad_req="null",
                          **{k: v.shape for k, v in {**feed, **warg}.items()})
    outs = exe.forward(is_train=False, **feed, **warg)
    expected = []
    prev = onp.zeros((B, 3), onp.float32)
    for t in range(T):
        nxt = outs[t].asnumpy()
        blended = prev * p + nxt * (1 - p)
        expected.append(blended)
        prev = blended  # reference tracks the BLENDED output
    # now the ZoneoutCell path with the SAME weights
    zo.reset()
    st = zo.begin_state()
    zo_steps = []
    for t in range(T):
        o, st = zo(x_syms[t], st)
        zo_steps.append(o)
    zgrp = mx.sym.Group(zo_steps)
    zexe = zgrp.simple_bind(grad_req="null",
                            **{k: v.shape for k, v in {**feed, **warg}.items()})
    zouts = zexe.forward(is_train=False, **feed, **warg)
    for t in range(T):
        np.testing.assert_allclose(zouts[t].asnumpy(), expected[t],
                                   rtol=1e-5, atol=1e-6)
    # t=0 sanity: (1-p)*out, NOT out*(1-p)^1-only-forever
    assert not np.allclose(zouts[1].asnumpy(),
                           outs[1].asnumpy() * (1 - p)), \
        "old attenuation formula detected at t=1"
    # reset() must clear the tracked output (fresh sequence)
    zo.reset()
    assert zo._prev_output is None
