"""tools/im2rec.py: list + rec phases, then the full input pipeline —
dataset built by im2rec, read back through ImageRecordIter's native C++
JPEG decoder at measured throughput (reference tools/im2rec.py +
src/io/iter_image_recordio_2.cc chain)."""
import os
import sys
import time

import numpy as np
import pytest
from PIL import Image

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _make_tree(root, classes=3, per_class=8, size=64):
    rng = np.random.RandomState(0)
    for c in range(classes):
        d = os.path.join(root, f"class{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.randint(0, 255, (size, size, 3), np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img{i}.jpg"),
                                      quality=90)


def test_im2rec_end_to_end(tmp_path):
    import im2rec

    import mxnet_tpu as mx
    from mxnet_tpu.io.recordio import MXIndexedRecordIO, unpack_img

    root = str(tmp_path / "images")
    os.makedirs(root)
    _make_tree(root)
    prefix = str(tmp_path / "data")

    assert im2rec.main([prefix, root, "--list", "--recursive"]) == 0
    assert os.path.exists(prefix + ".lst")
    lines = open(prefix + ".lst").read().strip().splitlines()
    assert len(lines) == 24

    assert im2rec.main([prefix, root, "--quality", "90"]) == 0
    rec = MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(rec.keys) == 24
    header, img = unpack_img(rec.read_idx(rec.keys[0]))
    assert img.shape == (64, 64, 3)
    assert float(header.label) in (0.0, 1.0, 2.0)

    # full pipeline: ImageRecordIter + native decoder
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 32, 32), batch_size=8,
                               resize=48, preprocess_threads=2)
    batch = next(iter(it))
    assert batch.data[0].shape == (8, 3, 32, 32)
    labels = batch.label[0].asnumpy()
    assert labels.shape == (8,)


def test_im2rec_multilabel_and_passthrough(tmp_path):
    import im2rec

    from mxnet_tpu.io.recordio import MXIndexedRecordIO, unpack

    root = str(tmp_path / "images")
    os.makedirs(root)
    _make_tree(root, classes=1, per_class=2)
    prefix = str(tmp_path / "ml")
    # hand-written multi-label .lst
    with open(prefix + ".lst", "w") as f:
        f.write("0\t1.0\t2.0\t3.0\tclass0/img0.jpg\n")
        f.write("1\t4.0\t5.0\t6.0\tclass0/img1.jpg\n")
    assert im2rec.main([prefix, root, "--pass-through"]) == 0
    rec = MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    header, payload = unpack(rec.read_idx(0))
    np.testing.assert_allclose(np.asarray(header.label), [1.0, 2.0, 3.0])
    assert payload[:2] == b"\xff\xd8"  # raw JPEG bytes preserved


def test_native_decode_throughput(tmp_path):
    """Pin the TRAINING-shape decode rate (224x224 from 256px sources, the
    bench configuration) — round 2's 96px/100-img/s floor would have passed
    on pure-PIL and pinned nothing. The uint8 wire path must clear a floor
    that PIL decode demonstrably cannot reach on this hardware (~1 core:
    PIL ≈ 120 img/s at this shape, native ≈ 900+)."""
    import im2rec

    import mxnet_tpu as mx
    from mxnet_tpu.native import io_lib

    if io_lib() is None:
        pytest.skip("native io library not built")
    root = str(tmp_path / "images")
    os.makedirs(root)
    _make_tree(root, classes=2, per_class=48, size=256)
    prefix = str(tmp_path / "tp")
    assert im2rec.main([prefix, root, "--list", "--recursive"]) == 0
    assert im2rec.main([prefix, root]) == 0

    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 224, 224), batch_size=32,
                               resize=256, rand_crop=True, rand_mirror=True,
                               preprocess_threads=2, dtype="uint8")
    assert it._native is not None, "native decoder must engage for u8 path"
    for batch in it:  # warm (first batch pays file open etc.)
        break
    n = 0
    t0 = time.perf_counter()
    try:
        while True:
            batch = next(it)
            n += batch.data[0].shape[0]
    except StopIteration:
        pass
    dt = time.perf_counter() - t0
    assert n >= 32
    rate = n / dt
    assert rate > 250, f"native u8 decode too slow: {rate:.0f} img/s"
