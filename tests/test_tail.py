"""Tail-based trace retention (``pytest -m blackbox`` / ``make prof``) —
docs/OBSERVABILITY.md "Tail sampling".

The retention policy as a pure function (every edge the budget/baseline/
force rules promise), the pending buffer's settle/straggler/expiry
semantics (a verdict racing replica-side buffer expiry must drop cleanly,
never error), the context-flag wire encoding (tail/force bits beside the
head-sampling bit), root-close verdict plumbing through the thread-local
outcome notes, OpenMetrics exemplars pinning retained trace ids to
latency buckets, the ``# HELP`` description registry, and the end-to-end
serve path: every span of a retained request — client, server, batcher,
engine — lands durably under ONE trace_id while a fast-path request's
spans are dropped on every hop.
"""
import random
import time

import numpy as np
import pytest

from mxnet_tpu import obs, serve
from mxnet_tpu import symbol as sym
from mxnet_tpu.obs import context, metrics, tail
from mxnet_tpu.obs.export import parts_to_prometheus, to_prometheus
from mxnet_tpu.obs.tail import RetentionPolicy, TailBuffer
from mxnet_tpu.serve import ServeClient, ServeServer

pytestmark = [pytest.mark.obs, pytest.mark.blackbox]


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    tail.disable()
    context.set_sample_rate(1.0)
    yield
    tail.disable()
    obs.disable()
    obs.reset()
    context.set_sample_rate(1.0)


def _keep_all():
    return RetentionPolicy(slow_ms=0.0, budget_per_s=1e9, burst=1e9,
                           baseline=0.0)


def _rec(name="s", tid=1):
    return ("X", name, 0.0, 0.001, tid, 1, {"trace_id": "t"})


# ---------------------------------------------------------------------------
# 1. the retention policy as a pure function
# ---------------------------------------------------------------------------

def test_policy_interesting_outcomes_retain():
    p = RetentionPolicy(slow_ms=1e9, budget_per_s=1e9, burst=1e9,
                        baseline=0.0)
    for outcome in ("error", "shed", "deadline"):
        retain, reason = p.decide(0.001, outcome=outcome)
        assert retain and reason == outcome


def test_policy_flags_and_latency_retain():
    p = RetentionPolicy(slow_ms=250.0, budget_per_s=1e9, burst=1e9,
                        baseline=0.0)
    assert p.decide(0.001, flags=("hedged",)) == (True, "hedged")
    assert p.decide(0.001, flags=("breaker",)) == (True, "breaker")
    assert p.decide(0.3) == (True, "slow")          # past the slow bar
    assert p.decide(0.001) == (False, "fast_path")  # below everything


def test_policy_budget_exhaustion_keeps_the_uniform_baseline():
    # burst of exactly 1 token, zero refill: the first interesting trace
    # consumes the budget ...
    p = RetentionPolicy(slow_ms=1e9, budget_per_s=0.0, burst=1.0,
                        baseline=1.0)
    assert p.decide(0.0, outcome="error", now=0.0) == (True, "error")
    # ... and past it an interesting trace degrades to the BASELINE
    # (probability 1 here), never to zero
    assert p.decide(0.0, outcome="error", now=0.0) == (True, "baseline")
    # with no baseline either, the honest answer is a counted budget drop
    p0 = RetentionPolicy(slow_ms=1e9, budget_per_s=0.0, burst=1.0,
                         baseline=0.0)
    p0.decide(0.0, outcome="error", now=0.0)
    assert p0.decide(0.0, outcome="error", now=0.0) == (False, "budget")


def test_policy_force_retain_bypasses_the_bucket():
    p = RetentionPolicy(slow_ms=1e9, budget_per_s=0.0, burst=0.0,
                        baseline=0.0)
    # zero tokens, zero baseline — force still keeps it
    assert p.decide(0.0, outcome="error", forced=True) == (True, "forced")
    # and consumed no budget: the next forced one is identical
    assert p.decide(0.0, forced=True) == (True, "forced")


def test_policy_token_bucket_refills_over_time():
    p = RetentionPolicy(slow_ms=1e9, budget_per_s=1.0, burst=1.0,
                        baseline=0.0)
    assert p.decide(0.0, outcome="error", now=0.0)[0] is True
    assert p.decide(0.0, outcome="error", now=0.5) == (False, "budget")
    # one full second of refill since the failed take → a token again
    assert p.decide(0.0, outcome="error", now=1.6)[0] is True


def test_policy_uniform_baseline_on_the_fast_path():
    keep = RetentionPolicy(slow_ms=1e9, budget_per_s=0.0, burst=0.0,
                           baseline=1.0, rng=random.Random(7))
    assert keep.decide(0.001) == (True, "baseline")
    drop = RetentionPolicy(slow_ms=1e9, budget_per_s=0.0, burst=0.0,
                           baseline=0.0)
    assert drop.decide(0.001) == (False, "fast_path")


# ---------------------------------------------------------------------------
# 2. the pending buffer: settle, stragglers, expiry races
# ---------------------------------------------------------------------------

def test_buffer_finish_promotes_whole_trace_to_the_ring():
    obs.enable()
    b = TailBuffer(policy=_keep_all())
    b.hold("t1", _rec("serve.rpc"))
    b.hold("t1", _rec("serve.execute"))
    assert b.pending_count() == 1
    retain, reason = b.finish("t1", 0.01)
    assert retain and reason == "slow"
    names = [r[1] for r in obs.trace.tracer.events()]
    assert names == ["serve.rpc", "serve.execute"]
    assert "t1" in b.retained_ids()


def test_buffer_drop_records_nothing():
    obs.enable()
    b = TailBuffer(policy=RetentionPolicy(slow_ms=1e9, baseline=0.0))
    b.hold("t1", _rec())
    assert b.finish("t1", 0.0)[0] is False
    assert obs.trace.tracer.events() == []
    assert b.pending_count() == 0


def test_buffer_straggler_span_follows_the_verdict():
    obs.enable()
    b = TailBuffer(policy=_keep_all())
    b.hold("kept", _rec("first"))
    b.finish("kept", 0.01)
    b.hold("kept", _rec("straggler"))      # raced the root close: kept
    assert [r[1] for r in obs.trace.tracer.events()] == ["first",
                                                         "straggler"]
    b2 = TailBuffer(policy=RetentionPolicy(slow_ms=1e9, baseline=0.0))
    b2.finish("dropped", 0.0)
    b2.hold("dropped", _rec("late"))       # dropped trace: span drops too
    assert b2.pending_count() == 0
    assert len(obs.trace.tracer.events()) == 2  # unchanged


def test_buffer_resolve_promotes_pending_replica_side():
    obs.enable()
    b = TailBuffer(policy=_keep_all())
    b.hold("t9", _rec("replica.span"))
    assert b.resolve(["t9", "unknown-id"]) == 1
    assert [r[1] for r in obs.trace.tracer.events()] == ["replica.span"]


def test_verdict_racing_buffer_expiry_drops_cleanly():
    """The satellite case: a replica held spans briefly, expired them,
    THEN the verdict arrived — resolve must be a counted no-op, and a
    straggler span for the expired trace must drop, never error."""
    obs.enable()
    b = TailBuffer(policy=_keep_all(), hold_s=0.01)
    b.hold("slowpoke", _rec())
    assert b.expire(now=time.monotonic() + 1.0) == 1
    assert b.expired == 1
    assert b.resolve(["slowpoke"]) == 0      # verdict lost the race
    b.hold("slowpoke", _rec("late"))         # straggler after expiry
    assert b.pending_count() == 0
    assert obs.trace.tracer.events() == []   # nothing ever promoted


def test_buffer_overflow_evicts_oldest_counted():
    b = TailBuffer(policy=_keep_all(), max_traces=2)
    for tid in ("a", "b", "c"):
        b.hold(tid, _rec())
    assert b.pending_count() == 2
    assert b.overflow == 1
    # the evicted trace can no longer promote
    assert b.resolve(["a"]) == 0


def test_buffer_caps_spans_per_trace():
    obs.enable()
    b = TailBuffer(policy=_keep_all(), max_spans=2)
    for i in range(5):
        b.hold("t", _rec(f"s{i}"))
    b.finish("t", 0.01)
    assert [r[1] for r in obs.trace.tracer.events()] == ["s0", "s1"]


# ---------------------------------------------------------------------------
# 3. context flags on the wire
# ---------------------------------------------------------------------------

def test_retained_log_scales_with_budget_and_hold_window():
    # the verdict log must cover everything the policy can retain within
    # one hold window, or the fan-out forgets verdicts before replicas
    # hear them and their held spans expire as drops
    b = TailBuffer(policy=RetentionPolicy(slow_ms=1e9, budget_per_s=50.0,
                                          burst=100.0, baseline=0.0),
                   hold_s=20.0)
    assert b._retained_log.maxlen >= 50 * 20 + 100
    # ...and a test's effectively-infinite budget stays bounded
    cap = TailBuffer(policy=_keep_all(), hold_s=20.0)
    assert cap._retained_log.maxlen == 65536


def test_finish_remote_retains_flagged_client_rooted_traces():
    """The front handling a CLIENT-rooted trace: hedge/breaker notes live
    on the front's handler thread and never reach the root's verdict
    (the reply status byte carries outcomes, not flags) — finish_remote
    applies the policy to the flags locally so the fleet-side spans of a
    hedged request survive, and the verdict fans out to the replicas."""
    obs.enable()
    tail.enable()
    tail.buffer().policy = RetentionPolicy(slow_ms=1e9, budget_per_s=1e9,
                                           burst=1e9, baseline=0.0)
    ctx = context.new_root()          # tail-flagged, root owned elsewhere
    tail.buffer().hold(ctx.trace_id, _rec("serve.rpc"))
    tail.note(hedged=True)
    out = tail.finish_remote(ctx, 0.001)
    assert out == (True, "hedged")
    assert ctx.trace_id in tail.retained_ids()
    assert metrics.registry.counter("tail.retained.hedged").value == 1
    assert [r[1] for r in obs.trace.tracer.events()] == ["serve.rpc"]
    # no flags → the trace stays PENDING (the root's slow/error verdict
    # may still promote it), and outcome notes alone are NOT re-decided
    # here — they rode the reply status to the root, which is
    # authoritative (double-deciding would spend budget twice)
    ctx2 = context.new_root()
    tail.buffer().hold(ctx2.trace_id, _rec("serve.rpc"))
    tail.note(outcome="deadline")
    assert tail.finish_remote(ctx2, 0.001) is None
    assert tail.buffer().pending_count() == 1
    assert tail.take_notes() == (None, set())   # ...but notes were cleared


def test_tail_and_force_flags_roundtrip_the_header():
    t, s = "a" * 32, "b" * 16
    for kw, bits in (({"sampled": True}, "01"),
                     ({"sampled": False, "tail": True}, "02"),
                     ({"sampled": True, "force": True}, "05")):
        ctx = context.TraceContext(t, s, **kw)
        h = ctx.to_header()
        assert h.endswith(f"-{bits}")
        back = context.from_header(h)
        assert back == ctx
        child = ctx.child()
        assert (child.tail, child.force, child.sampled) == \
            (ctx.tail, ctx.force, ctx.sampled)


def test_new_root_under_tail_mode_pends_instead_of_sampling():
    context.set_sample_rate(0.0)    # head sampling would record NOTHING
    tail.enable()
    ctx = context.new_root()
    assert ctx.tail and not ctx.sampled and ctx.records
    tail.disable()
    assert context.new_root().sampled is False   # head mode again


def test_tail_context_without_local_buffer_records_nothing():
    """A tail-bit context arriving over the wire at a process that never
    enabled tail mode must DROP, not record durably: there is no buffer
    to hold the spans, no verdict will ever promote them, and recording
    would silently bypass this process's own head-sampling rate."""
    obs.enable()
    assert not tail.enabled()
    ctx = context.TraceContext(context.new_trace_id(),
                               context.new_span_id(),
                               sampled=False, tail=True)
    with context.use(ctx):
        with obs.trace.span("serve.execute"):
            pass
    assert [e for e in obs.trace.drain() if e["ph"] == "X"] == []


def test_forced_block_births_force_retain_roots():
    tail.enable()
    with tail.forced():
        ctx = context.new_root()
    assert ctx.force and ctx.sampled and not ctx.tail
    assert context.new_root().force is False     # scope ended


# ---------------------------------------------------------------------------
# 4. root-close verdicts: notes, finish_root, exemplars
# ---------------------------------------------------------------------------

def test_finish_root_merges_thread_notes():
    obs.enable()
    tail.enable()
    tail.buffer().policy = RetentionPolicy(slow_ms=1e9, budget_per_s=1e9,
                                           burst=1e9, baseline=0.0)
    ctx = context.new_root()
    tail.note("deadline")
    tail.note(hedged=True)
    retain, reason = tail.finish_root(ctx, 0.001)
    assert retain and reason == "deadline"   # outcome outranks the flags


def test_finish_root_none_clears_notes_without_a_verdict():
    tail.enable()
    tail.note("error")
    assert tail.finish_root(None, 0.0) is None
    # the notes were consumed: the next request on this thread is clean
    assert tail.take_notes() == (None, set())


def test_note_is_a_noop_with_tail_mode_off():
    """A note written while nothing will ever consume it (tail mode off:
    the server's shed/deadline branches still run, finish_root may never
    fire) must not sit in the thread's TLS and contaminate the first
    request after a later enable()."""
    tail.disable()
    tail.note("shed", breaker=True)
    assert tail.take_notes() == (None, set())
    tail.enable()
    try:
        assert tail.take_notes() == (None, set())
    finally:
        tail.disable()


def test_finish_root_logs_forced_verdicts():
    """A force-retained root records durably span by span — but its
    verdict must STILL be logged (and counted) so the telemetry plane
    distributes it to the other hops' pending buffers."""
    obs.enable()
    tail.enable()
    with tail.forced():
        ctx = context.new_root()
        with context.use(ctx):
            with obs.trace.span("serve.client.rpc"):
                pass
    assert tail.finish_root(ctx, 0.001) == (True, "forced")
    assert ctx.trace_id in tail.retained_ids()
    st = tail.stats()
    assert st["retained"] == 1


def test_retained_trace_stamps_bucket_exemplar():
    obs.enable()
    tail.enable()
    tail.buffer().policy = _keep_all()
    metrics.registry.histogram("serve.latency_seconds").observe(0.04)
    ctx = context.new_root()
    with context.use(ctx):
        with obs.trace.span("serve.client.rpc"):
            pass
    tail.finish_root(ctx, 0.04)
    ex = tail.exemplars_snapshot()
    by_le = ex["serve.latency_seconds"]
    (entry,) = by_le.values()
    assert entry["trace_id"] == ctx.trace_id
    # ... and the exposition renders it as an OpenMetrics exemplar
    text = to_prometheus(metrics.snapshot(), exemplars=ex)
    assert f'# {{trace_id="{ctx.trace_id}"}}' in text
    # telemetry parts carry exemplars + tail stats for the fleet plane
    part = obs.telemetry_part(drain=False)
    assert part["exemplars"] == ex
    assert part["tail"]["retained"] == 1
    assert f'trace_id="{ctx.trace_id}"' in parts_to_prometheus([part])
    # OpenMetrics output carries the required EOF terminator
    assert text.endswith("# EOF\n")
    # strict text format 0.0.4: exemplars are a MID-LINE '#', which a
    # 0.0.4 parser rejects as a whole-scrape error — openmetrics=False
    # must emit none (and no EOF marker either)
    strict = to_prometheus(metrics.snapshot(), exemplars=ex,
                           openmetrics=False)
    assert "trace_id" not in strict and "# EOF" not in strict
    assert all(ln.startswith("#") or "#" not in ln
               for ln in strict.splitlines())


def test_exemplar_on_an_unrendered_bucket_attaches_to_the_next_one():
    """A shed/deadline verdict retains the trace WITHOUT observing its
    latency into the histogram, so the exemplar's exact bucket is often
    empty — and empty buckets are omitted from the snapshot. The
    exposition must re-key such an exemplar onto the first rendered
    bucket that still contains its value (``value <= le`` is all
    OpenMetrics requires), not silently drop it."""
    obs.enable()
    tail.enable()
    h = metrics.registry.histogram("serve.latency_seconds")
    h.observe(0.04)                      # ONLY the 0.05 bucket renders
    # a shed request's exemplar: 10µs lands in the (empty, unrendered)
    # first bucket
    tail._record_exemplar("e" * 32, 1e-05)
    text = to_prometheus(metrics.snapshot(),
                         exemplars=tail.exemplars_snapshot())
    lines = [ln for ln in text.splitlines() if 'trace_id="' + "e" * 32 in ln]
    assert len(lines) == 1, text
    assert "serve_latency_seconds_bucket" in lines[0]
    # ...and a value past every rendered bound rides the +Inf bucket
    tail.reset()
    tail._record_exemplar("f" * 32, 1e9)
    text = to_prometheus(metrics.snapshot(),
                         exemplars=tail.exemplars_snapshot())
    (inf_line,) = [ln for ln in text.splitlines()
                   if 'trace_id="' + "f" * 32 in ln]
    assert 'le="+Inf"' in inf_line


def test_help_lines_from_description_registry():
    metrics.registry.counter("fleet.requests").inc()
    metrics.registry.counter("kvstore.rpc.retries").inc()
    metrics.registry.histogram("kvstore.rpc.push_seq_seconds").observe(0.01)
    metrics.registry.counter("totally.undocumented.thing").inc()
    text = to_prometheus(metrics.snapshot())
    assert ("# HELP mxnet_fleet_requests "
            "requests routed by the fleet router") in text
    assert "# HELP mxnet_kvstore_rpc_retries" in text
    # family-prefix match covers dynamically named RPC histograms
    assert ("# HELP mxnet_kvstore_rpc_push_seq_seconds "
            "PS client-side RPC latency per opcode") in text
    # undescribed metrics render exactly as before — TYPE but no HELP
    assert "# TYPE mxnet_totally_undocumented_thing counter" in text
    assert "# HELP mxnet_totally_undocumented_thing" not in text
    # the runtime registration hook wins over nothing
    metrics.describe("totally.undocumented.thing", "now it is")
    assert ("# HELP mxnet_totally_undocumented_thing now it is"
            in to_prometheus(metrics.snapshot()))


# ---------------------------------------------------------------------------
# 5. end to end over the serve wire (client + server share this process's
#    buffer — the verdict settles every hop's spans at once)
# ---------------------------------------------------------------------------

def _serve_pair():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, no_bias=True, name="fc")
    arg = {"fc_weight": np.eye(4, dtype=np.float32)}
    engine = serve.InferenceEngine(net, arg, max_batch_size=8, lint="off")
    srv = ServeServer(engine, port=0, max_linger_ms=0.0)
    srv.start()
    return srv, ServeClient("127.0.0.1", srv.port)


X = np.arange(8, dtype=np.float32).reshape(2, 4)


def test_serve_retained_request_keeps_every_hop_one_trace_id():
    obs.enable()
    tail.enable()
    tail.buffer().policy = _keep_all()     # everything is "interesting"
    srv, cli = _serve_pair()
    try:
        np.testing.assert_array_equal(cli.infer(X), X)
    finally:
        cli.close()
        srv.stop()
    spans = {e["name"]: e["args"] for e in obs.trace.drain()
             if e["ph"] == "X" and e.get("args")}
    for name in ("serve.client.rpc", "serve.rpc", "serve.queue_wait",
                 "serve.execute", "serve.serialize"):
        assert name in spans, f"missing {name}"
    tids = {s["trace_id"] for s in spans.values() if "trace_id" in s}
    assert len(tids) == 1
    st = tail.stats()
    assert st["retained"] >= 1 and st["pending"] == 0


def test_serve_fast_path_request_drops_every_hop():
    obs.enable()
    tail.enable()
    tail.buffer().policy = RetentionPolicy(slow_ms=1e9, budget_per_s=1e9,
                                           burst=1e9, baseline=0.0)
    srv, cli = _serve_pair()
    try:
        np.testing.assert_array_equal(cli.infer(X), X)
    finally:
        cli.close()
        srv.stop()
    # a healthy fast request leaves NO durable spans on any hop — but the
    # verdict was a real decision, not a recording gap
    serve_spans = [e for e in obs.trace.drain()
                   if e["ph"] == "X" and e["name"].startswith("serve.")]
    assert serve_spans == []
    st = tail.stats()
    assert st["dropped"] >= 1 and st["pending"] == 0


def test_serve_telemetry_resolves_retained_ids_before_drain():
    """The cross-process promotion path, driven in one process: spans held
    pending under a trace id promote when OP_TELEMETRY carries the verdict
    list, and leave with that very collection."""
    obs.enable()
    tail.enable()
    tail.buffer().policy = RetentionPolicy(slow_ms=1e9, budget_per_s=1e9,
                                           burst=1e9, baseline=0.0)
    srv, cli = _serve_pair()
    try:
        np.testing.assert_array_equal(cli.infer(X), X)  # dropped locally...
        # ...but fish the trace id out while it is still settled-dropped:
        # simulate a REPLICA whose root lives elsewhere by re-pending spans
        tail.reset()
        ctx = context.new_root()
        with context.use(ctx):
            with obs.trace.span("serve.execute"):
                pass
        assert tail.buffer().pending_count() == 1
        tel = cli.telemetry(drain=True, retained=[ctx.trace_id])
        (part,) = tel["parts"]
        promoted = [s for s in part["spans"]
                    if s.get("name") == "serve.execute"]
        assert promoted, "verdict-promoted span missing from the part"
        assert promoted[0]["args"]["trace_id"] == ctx.trace_id
        assert tail.buffer().pending_count() == 0
    finally:
        cli.close()
        srv.stop()


def test_serve_telemetry_strict_prometheus_over_the_wire():
    """``openmetrics=False`` rides the OP_TELEMETRY spec: the reply is
    strict text format 0.0.4 — no exemplar suffixes, no ``# EOF`` — so it
    can feed a node_exporter textfile collector without re-rendering."""
    obs.enable()
    tail.enable()
    tail.buffer().policy = _keep_all()
    srv, cli = _serve_pair()
    try:
        np.testing.assert_array_equal(cli.infer(X), X)  # retained → exemplar
        om = cli.telemetry(drain=False, fmt="prometheus")
        assert om.rstrip().endswith("# EOF")
        assert 'trace_id="' in om      # the exemplar rode the wire
        strict = cli.telemetry(drain=False, fmt="prometheus",
                               openmetrics=False)
        assert "# EOF" not in strict
        assert 'trace_id="' not in strict
        assert "mxnet_serve_latency_seconds_bucket" in strict
    finally:
        cli.close()
        srv.stop()
