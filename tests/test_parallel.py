"""parallel/ tests on the 8-virtual-device CPU mesh (conftest).

The reference's analog is the dist kvstore nightly tests run via the local
tracker (SURVEY.md §4); here the assertions are numeric equivalence between
sharded and single-device execution.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon import nn
from mxnet_tpu.models import bert_tiny, bert_sharding_rules, TransformerLM


def test_make_mesh():
    mesh = par.make_mesh({"dp": 2, "tp": 4})
    assert par.mesh_axes(mesh) == {"dp": 2, "tp": 4}
    mesh = par.make_mesh({"dp": -1, "tp": 2})
    assert par.mesh_axes(mesh) == {"dp": 4, "tp": 2}
    # fully-specified mesh smaller than the host takes a device subset
    # (reference analog: ctx=[mx.gpu(i) for i in ...])
    mesh = par.make_mesh({"dp": 3})
    assert par.mesh_axes(mesh) == {"dp": 3}
    assert mesh.devices.size == 3
    with pytest.raises(ValueError):
        par.make_mesh({"dp": 16})


def test_sharding_rules_pruning():
    rules = bert_sharding_rules()
    mesh = par.make_mesh({"dp": 2, "tp": 4})
    assert rules.spec_for("bert0_enc_layer0_attn_qkv_weight", (192, 64), mesh) \
        == P("tp")  # trailing None pruned
    assert rules.spec_for("bert0_enc_layer0_attn_proj_weight", (64, 64), mesh) \
        == P(None, "tp")
    # axis that does not divide -> replicated
    assert rules.spec_for("x_qkv_weight", (6, 5), mesh) == P()
    # mesh without tp -> replicated
    dp_mesh = par.make_mesh({"dp": 8})
    assert rules.spec_for("bert0_enc_layer0_attn_qkv_weight", (192, 64), dp_mesh) == P()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_plain(causal):
    from mxnet_tpu.parallel.ring_attention import plain_attention

    B, H, S, D = 2, 2, 16, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    ref = plain_attention(q, k, v, causal=causal)
    mesh = par.make_mesh({"sp": 8})
    out = par.sequence_sharded_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-5)


def test_ring_attention_dp_tp_sp_mesh():
    from mxnet_tpu.parallel.ring_attention import plain_attention

    B, H, S, D = 2, 2, 8, 4
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    mesh = par.make_mesh({"dp": 2, "sp": 2, "tp": 2})
    out = par.sequence_sharded_attention(q, k, v, mesh, causal=False)
    ref = plain_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-5)


def test_functionalize_batchnorm_aux():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.BatchNorm(in_channels=4))
    net.initialize()
    net(nd.ones((2, 3)))  # resolve shapes
    names, apply = par.functionalize(net, train=True)
    vals = {p.name: p.data()._data for p in net._iter_params()}
    out, aux = apply(vals, jnp.ones((2, 3)))
    assert any("running_mean" in k for k in aux)
    assert any("running_var" in k for k in aux)


def test_sharded_trainer_dp_matches_serial():
    """DP-sharded step == single-device SGD (the known-value kvstore test idea)."""

    def build():
        mx.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=8), nn.Dense(4, in_units=16))
        net.initialize()
        return net

    rng = np.random.RandomState(3)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, 16).astype(np.int32)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    # serial reference via autograd + plain SGD math
    net_a = build()
    with mx.autograd.record():
        loss = loss_fn(net_a(nd.array(x)), nd.array(y)).mean()
    loss.backward()
    lr = 0.5
    expected = {k: p.data().asnumpy() - lr * p.grad().asnumpy()
                for k, p in net_a._collect_params_with_prefix().items()}

    net_b = build()
    mesh = par.make_mesh({"dp": 8})
    trainer = par.ShardedTrainer(net_b, loss_fn, mesh, optimizer="sgd",
                                 optimizer_params={"learning_rate": lr})
    step_loss = trainer.step(nd.array(x), nd.array(y))
    assert np.isfinite(float(step_loss.asnumpy()))
    trainer.sync_to_net()
    for k, p in net_b._collect_params_with_prefix().items():
        np.testing.assert_allclose(p.data().asnumpy(), expected[k],
                                   rtol=1e-4, atol=1e-5)


def test_sharded_trainer_bert_dp_tp_sp():
    """Full train step of the flagship on a dp×sp×tp mesh; loss decreases."""
    net = bert_tiny(vocab_size=100, dropout=0.0, max_length=32)
    net.initialize()
    x = nd.array(np.random.RandomState(0).randint(0, 100, (8, 16)).astype(np.int32))
    net(x)  # resolve deferred shapes
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = par.make_mesh({"dp": 2, "sp": 2, "tp": 2})
    trainer = par.ShardedTrainer(net, loss_fn, mesh, rules=bert_sharding_rules(),
                                 optimizer="adam",
                                 optimizer_params={"learning_rate": 1e-3})
    labels = x  # autoencoding objective for the smoke test
    losses = [float(trainer.step(x, labels).asnumpy()) for _ in range(5)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_transformer_lm_is_causal():
    net = TransformerLM(vocab_size=50, units=32, hidden_size=64, num_layers=1,
                        num_heads=2, max_length=16, dropout=0.0)
    net.initialize()
    x1 = np.zeros((1, 8), np.int32)
    x2 = x1.copy()
    x2[0, -1] = 7  # change only the LAST token
    o1 = net(nd.array(x1)).asnumpy()
    o2 = net(nd.array(x2)).asnumpy()
    # earlier positions must be unaffected by the future token
    np.testing.assert_allclose(o1[0, :-1], o2[0, :-1], rtol=1e-5, atol=1e-6)
    assert np.abs(o1[0, -1] - o2[0, -1]).max() > 1e-4


def test_bert_forward_shape():
    net = bert_tiny(vocab_size=64, max_length=32)
    net.initialize()
    out = net(nd.array(np.zeros((2, 10), np.int32)))
    assert out.shape == (2, 10, 64)


def test_sharded_trainer_bf16_compute():
    """AMP: bf16 fwd/bwd, fp32 master weights, loss decreases."""
    import jax.numpy as jnp

    net = bert_tiny(vocab_size=64, dropout=0.0, max_length=32)
    net.initialize()
    x = nd.array(np.random.RandomState(1).randint(0, 64, (4, 16)).astype(np.int32))
    net(x)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = par.make_mesh({"dp": 2}, devices=__import__("jax").devices()[:2])
    trainer = par.ShardedTrainer(net, loss_fn, mesh, optimizer="adam",
                                 optimizer_params={"learning_rate": 1e-3},
                                 compute_dtype="bfloat16")
    losses = [float(trainer.step(x, x).asnumpy()) for _ in range(5)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    # master weights stay fp32 across steps (incl. BN/LN aux merges)
    for n, v in trainer.param_vals.items():
        if jnp.issubdtype(v.dtype, jnp.floating):
            assert v.dtype == jnp.float32, (n, v.dtype)


def test_sharded_trainer_bf16_conv_bn():
    """AMP on a conv+BN net — the ResNet-shaped path that crashed in round 2
    (bf16 conv input meeting f32 BN output / frozen deferred BN params)."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1, in_channels=3))
        net.add(nn.BatchNorm())  # in_channels deferred — the failing config
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2D(8, 3, padding=1, in_channels=8))
        net.add(nn.BatchNorm())
        net.add(nn.Flatten())
        net.add(nn.Dense(4))
    net.initialize()
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = par.make_mesh({"dp": 2}, devices=jax.devices()[:2])
    trainer = par.ShardedTrainer(net, loss_fn, mesh, optimizer="sgd",
                                 optimizer_params={"learning_rate": 0.1,
                                                   "momentum": 0.9},
                                 compute_dtype="bfloat16")
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(4, 3, 8, 8).astype(np.float32))
    y = nd.array(rng.randint(0, 4, 4).astype(np.int32))
    losses = [float(trainer.step(x, y).asnumpy()) for _ in range(5)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    gammas = [n for n in trainer.param_vals if "gamma" in n]
    rmeans = [n for n in trainer.param_vals if "running_mean" in n]
    assert gammas and rmeans
    # BN scale/shift are trained (deferred params captured), stats stay f32
    # master dtype and actually move
    for g in gammas:
        assert g in trainer._grad_names
    for rm in rmeans:
        assert trainer.param_vals[rm].dtype == jnp.float32
        assert bool(jnp.any(trainer.param_vals[rm] != 0))


def test_sharded_trainer_preprocess_uint8():
    """preprocess= fuses input normalization into the step program: uint8
    batches train a conv+BN net (deferred shapes resolve through preprocess)."""
    import jax.numpy as jnp

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1, in_channels=3))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Flatten())
        net.add(nn.Dense(4))
    net.initialize()
    mean = jnp.asarray(np.full((3, 1, 1), 128.0, np.float32))

    def preprocess(x):
        if x.dtype == jnp.uint8:
            return (x.astype(jnp.float32) - mean) / 64.0
        return x

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = par.make_mesh({"dp": 2}, devices=jax.devices()[:2])
    trainer = par.ShardedTrainer(net, loss_fn, mesh, optimizer="sgd",
                                 optimizer_params={"learning_rate": 0.1},
                                 preprocess=preprocess)
    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, 255, (8, 3, 8, 8)).astype(np.uint8))
    y = nd.array(rng.randint(0, 4, 8).astype(np.float32))  # f32 labels: in-jit cast
    losses = [float(trainer.step(x, y).asnumpy()) for _ in range(5)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_sharded_trainer_remat_matches_plain():
    """remat=True (jax.checkpoint over the forward) must train identically
    to the plain step — only memory/recompute differ."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.models import transformer_lm

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, 40, (2, 16)).astype(np.int32))
    losses = {}
    for remat in (False, True):
        mx.random.seed(11)
        net = transformer_lm(vocab_size=40, units=16, hidden_size=32,
                             num_layers=1, num_heads=2, max_length=16,
                             dropout=0.0)
        net.initialize()
        mesh = par.make_mesh({"dp": 1})
        trainer = par.ShardedTrainer(
            net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), mesh,
            optimizer="adam", optimizer_params={"learning_rate": 1e-2},
            remat=remat)
        ls = [float(trainer.step(x, x).asnumpy()) for _ in range(3)]
        losses[remat] = ls
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
    assert losses[True][-1] < losses[True][0]


def test_grad_accum_matches_full_batch():
    """grad_accum=k (micro-batch scan, one update) must produce the same
    parameters as the monolithic full-batch step (CE-mean losses average
    exactly over equal micro-batches; no BN in the net)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu import parallel as par

    def build(grad_accum):
        mx.random.seed(0)
        net = mx.gluon.nn.HybridSequential()
        net.add(mx.gluon.nn.Dense(16, in_units=8))
        net.add(mx.gluon.nn.Dense(4, in_units=16))
        net.initialize()
        loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
        mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
        return net, par.ShardedTrainer(
            net, loss_fn, mesh, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1}, donate=False,
            grad_accum=grad_accum)

    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(8, 8).astype(np.float32))
    y = nd.array(rng.randint(0, 4, 8).astype(np.int32))

    net1, t1 = build(1)
    l1 = t1.step(x, y)
    net4, t4 = build(4)
    l4 = t4.step(x, y)
    np.testing.assert_allclose(float(l1.asnumpy()), float(l4.asnumpy()),
                               rtol=1e-5)
    # align by the trainers' structural order: param_vals returns from the
    # jitted step with pytree-SORTED keys, and lexicographic order flips
    # when the global name counter crosses a decade (dense10 < dense9)
    v1 = [t1.param_vals[n] for n in t1._grad_names]
    v4 = [t4.param_vals[n] for n in t4._grad_names]
    for i, (a, b) in enumerate(zip(v1, v4)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"param #{i} diverged")


def test_grad_accum_rejects_indivisible_batch():
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu import parallel as par

    net = mx.gluon.nn.Dense(2, in_units=3)
    net.initialize()
    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    tr = par.ShardedTrainer(net, mx.gluon.loss.L2Loss(), mesh,
                            grad_accum=3)
    x = nd.array(np.ones((4, 3), np.float32))
    y = nd.array(np.ones((4, 2), np.float32))
    with pytest.raises(Exception, match="grad_accum"):
        tr.step(x, y)
